"""xDS: an xds resolver + EDS endpoint discovery over the real ADS wire.

The reference carries the xDS client_channel family — the ``xds:`` resolver
(``ext/filters/client_channel/resolver/xds/xds_resolver.cc``), the xds LB
policies (``lb_policy/xds/{cds,eds}.cc``) and the google-c2p variant — as
inherited inventory (SURVEY.md §2.4). This module is tpurpc's analog: the
gRPC xds UX (bootstrap file + ``xds:///service`` targets + dynamic
endpoint updates into the channel's composition tree), speaking — as of
round 5 — the REAL v3 ADS protobuf stream for EDS
(``AggregatedDiscoveryService/StreamAggregatedResources`` carrying
``ClusterLoadAssignment``, hand-rolled codec in
:mod:`tpurpc.rpc.xds_v3`), so a stock control plane can feed endpoints.
LDS/RDS/CDS and the google-c2p resolver remain scoped out (ecosystem
surface, VERDICT r4 next #7); the legacy ADS-lite JSON wire stays
available behind bootstrap ``server_features: ["ads_lite"]``.

Pieces (mirroring how gRPC's pieces fit):

* **Bootstrap** — ``GRPC_XDS_BOOTSTRAP`` (a JSON file path) or
  ``GRPC_XDS_BOOTSTRAP_CONFIG`` (inline JSON), the real gRPC knobs:
  ``{"xds_servers": [{"server_uri": "host:port"}], "node": {"id": ...}}``.
* **``xds:`` resolver** — registered into the channel's resolver registry
  (``register_resolver``, the fake-resolver seam): ``xds:///service``
  dials the bootstrap server and returns the service's CURRENT endpoint
  list — so a plain ``Channel("xds:///service")`` works with a static
  snapshot, grpcio-style.
* **:class:`XdsServicer`** — the control plane: per-service endpoint
  sets pushed to subscribers (``set_endpoints`` = the EDS
  ClusterLoadAssignment update). Attach to any tpurpc server.
* **:class:`XdsWatcher`** — the dynamic half: subscribes on the ADS-lite
  stream and feeds every update into ``Channel.update_addresses`` (the
  eds policy's job in the reference).
* **:func:`xds_channel`** — the one-call UX: bootstrap + first snapshot +
  watcher, returning a channel whose membership tracks the control plane.

Wire (ADS-lite): bidi stream ``/tpurpc.xds.v1.Ads/Stream``; the client
opens with ``{"node": {...}, "resource": "<service>"}`` (JSON) and
receives ``{"version": N, "endpoints": ["host:port", ...]}`` — the
current assignment immediately, then one message per change.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence

from tpurpc.analysis.locks import make_condition

METHOD = "/tpurpc.xds.v1.Ads/Stream"


# -- bootstrap ---------------------------------------------------------------

def load_bootstrap() -> dict:
    """The gRPC bootstrap contract: file via GRPC_XDS_BOOTSTRAP, inline
    via GRPC_XDS_BOOTSTRAP_CONFIG (file wins, like gRPC)."""
    path = os.environ.get("GRPC_XDS_BOOTSTRAP")
    raw: Optional[str] = None
    if path:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    else:
        raw = os.environ.get("GRPC_XDS_BOOTSTRAP_CONFIG")
    if not raw:
        raise RuntimeError(
            "xds: target needs a bootstrap: set GRPC_XDS_BOOTSTRAP to a "
            "JSON file or GRPC_XDS_BOOTSTRAP_CONFIG to inline JSON")
    cfg = json.loads(raw)
    servers = cfg.get("xds_servers") or []
    if not servers or "server_uri" not in servers[0]:
        raise RuntimeError("xds bootstrap needs xds_servers[0].server_uri")
    return cfg


def _server_uri(cfg: dict) -> str:
    return cfg["xds_servers"][0]["server_uri"]


# -- control plane -----------------------------------------------------------

class XdsServicer:
    """ADS-lite control plane: per-service endpoint assignments, pushed.

    ``set_endpoints(service, ["h:p", ...])`` is the EDS update; every
    subscriber of that service receives the new assignment immediately,
    and a fresh subscriber gets the current one on subscribe."""

    #: lock map, checked by `python -m tpurpc.analysis` (lint rule `lock`)
    _GUARDED_BY = {"_assignments": "_lock", "_version": "_lock"}

    def __init__(self):
        self._lock = make_condition("XdsServicer._lock")
        self._assignments: Dict[str, List[str]] = {}
        self._version = 0

    def set_endpoints(self, service: str, endpoints: Sequence[str]) -> None:
        with self._lock:
            self._assignments[service] = list(endpoints)
            self._version += 1
            self._lock.notify_all()

    def get_endpoints(self, service: str) -> List[str]:
        with self._lock:
            return list(self._assignments.get(service, []))

    def _stream(self, request_iterator, ctx):
        first = next(iter(request_iterator), None)
        if first is None:
            return
        try:
            sub = json.loads(bytes(first).decode())
            resource = sub["resource"]
        except (ValueError, KeyError):
            from tpurpc.rpc.status import AbortError, StatusCode

            raise AbortError(StatusCode.INVALID_ARGUMENT,
                             "ADS stream must open with "
                             '{"resource": "<service>"}') from None
        last_sent: Optional[List[str]] = None
        while ctx.is_active():
            with self._lock:
                current = list(self._assignments.get(resource, []))
                version = self._version
                if current == last_sent:
                    self._lock.wait_for(lambda: self._version != version,
                                        timeout=1.0)
                    continue
            last_sent = current
            yield json.dumps({"version": version,
                              "endpoints": current}).encode()

    def _stream_v3(self, request_iterator, ctx):
        """The REAL wire: v3 ADS ``StreamAggregatedResources`` (round 5,
        VERDICT r4 next #7). Subscribes are DiscoveryRequests (hand-rolled
        codec, :mod:`tpurpc.rpc.xds_v3` — the lb_v1 pattern); pushes are
        DiscoveryResponses carrying ClusterLoadAssignment Anys. A reader
        thread drains ACKs/resubscriptions so the push loop never blocks
        on the request side (real clients ACK every response)."""
        from tpurpc.rpc import xds_v3

        subscribed: List[str] = []
        sub_changed = threading.Event()
        req_iter = iter(request_iterator)
        first = next(req_iter, None)
        if first is None:
            return
        req = xds_v3.decode_discovery_request(first)
        if req["type_url"] not in ("", xds_v3.CLA_TYPE_URL):
            from tpurpc.rpc.status import AbortError, StatusCode

            raise AbortError(
                StatusCode.UNIMPLEMENTED,
                f"only {xds_v3.CLA_TYPE_URL} is served") from None
        subscribed = req["resource_names"]

        def drain_requests():
            # ACKs and resubscriptions; a resource_names change re-arms
            # the push loop (the A* protocols allow re-subscribing on the
            # same stream). The mutation, the flag, and the wakeup are ONE
            # critical section with the push loop's snapshot+clear — a
            # resubscription can land entirely before or entirely after a
            # snapshot, never half inside it (ADVICE r5: the unlocked
            # mutation relied on the 1 s wait timeout to be observed).
            for raw in req_iter:
                upd = xds_v3.decode_discovery_request(raw)
                if not upd["resource_names"]:
                    continue
                with self._lock:
                    # the compare must sit INSIDE the critical section too:
                    # comparing against `subscribed` unlocked reads the list
                    # while the push loop's snapshot may observe it — the
                    # residual window of the round-5 fix (ISSUE 2 satellite)
                    if upd["resource_names"] != subscribed:
                        subscribed[:] = upd["resource_names"]
                        sub_changed.set()
                        self._lock.notify_all()

        threading.Thread(target=drain_requests, daemon=True,
                         name="tpurpc-ads-v3-reader").start()
        last_sent: Optional[List[tuple]] = None
        nonce = 0
        while ctx.is_active():
            with self._lock:
                current = [(name, tuple(self._assignments.get(name, [])))
                           for name in subscribed]
                version = self._version
                # Re-check AND clear under the same lock as the snapshot:
                # the snapshot above already reflects any subscription the
                # flag announced (both mutate under self._lock), so clearing
                # here cannot eat a change the snapshot missed; one landing
                # after release simply re-sets the flag for the next lap.
                changed = sub_changed.is_set()
                if changed:
                    sub_changed.clear()
                if current == last_sent and not changed:
                    self._lock.wait_for(lambda: self._version != version,
                                        timeout=1.0)
                    continue
            last_sent = current
            nonce += 1
            yield xds_v3.encode_discovery_response(
                [(name, list(addrs)) for name, addrs in current],
                version_info=str(version), nonce=str(nonce))

    def attach(self, server) -> None:
        from tpurpc.rpc import xds_v3
        from tpurpc.rpc.server import stream_stream_rpc_method_handler

        server.add_method(METHOD,
                          stream_stream_rpc_method_handler(self._stream))
        server.add_method(xds_v3.METHOD,
                          stream_stream_rpc_method_handler(self._stream_v3))


# -- client side -------------------------------------------------------------

def _use_ads_lite(cfg: dict) -> bool:
    """Wire selection from the bootstrap: the REAL v3 ADS protobuf stream
    is the default (a stock control plane can serve it); the legacy JSON
    ADS-lite wire is opt-in via ``server_features: ["ads_lite"]`` (the
    gRPC bootstrap's server_features mechanism, repurposed)."""
    feats = (cfg.get("xds_servers") or [{}])[0].get("server_features", [])
    return "ads_lite" in feats


def _fetch_first(server_uri: str, method: str, sub: bytes, service: str,
                 timeout: float) -> bytes:
    """Shared snapshot-fetch skeleton: open ``method``, send ``sub``, HOLD
    the request side open until the first response lands (a generator that
    returns right after the subscribe half-closes immediately, and a
    strict control plane may treat client half-close as end-of-stream
    before its first push — ADVICE r4 #5), cancel on every exit path, and
    return the first message's bytes. One copy of this subtle lifecycle
    for both wires (reviewer finding, round 5)."""
    from tpurpc.rpc.channel import Channel

    with Channel(server_uri, connect_timeout=timeout) as ch:
        done = threading.Event()

        def reqs():
            yield sub
            done.wait(timeout)

        call = ch.stream_stream(method)(reqs(), timeout=timeout)
        try:
            first = next(iter(call), None)
        finally:
            done.set()
            try:
                call.cancel()
            except Exception:
                pass
        if first is None:
            raise RuntimeError(
                f"xds server {server_uri} closed the ADS stream without "
                f"an assignment for {service!r}")
        return bytes(first)


def _fetch_snapshot_v3(server_uri: str, service: str, node: dict,
                       timeout: float = 10.0) -> List[str]:
    """One v3 ADS subscribe → first ClusterLoadAssignment → done."""
    from tpurpc.rpc import xds_v3

    sub = xds_v3.encode_discovery_request(
        [service], node_id=str(node.get("id", "")),
        node_cluster=str(node.get("cluster", "")))
    first = _fetch_first(server_uri, xds_v3.METHOD, sub, service, timeout)
    upd = xds_v3.decode_discovery_response(first)
    if service not in upd["assignments"]:
        raise RuntimeError(
            f"ADS response from {server_uri} carries no "
            f"ClusterLoadAssignment for {service!r}")
    return list(upd["assignments"][service])


def _fetch_snapshot(server_uri: str, service: str, node: dict,
                    timeout: float = 10.0) -> List[str]:
    """One subscribe → first assignment → done (the resolver's job).
    Legacy ADS-lite JSON wire (bootstrap ``server_features: ["ads_lite"]``)."""
    sub = json.dumps({"node": node, "resource": service}).encode()
    first = _fetch_first(server_uri, METHOD, sub, service, timeout)
    try:
        return list(json.loads(first.decode())["endpoints"])
    except (ValueError, KeyError) as exc:
        raise RuntimeError(
            f"malformed ADS response from {server_uri}") from exc


def _normalize(endpoints: Sequence[str]) -> list:
    """Endpoint strings → resolved (host, port) tuples, through the SAME
    normalization ``Channel.update_addresses`` applies — hostname
    endpoints must produce identical keys at construction and on every
    update, or the keep-live matching misses and a no-op update tears
    down live connections (channel.py's own warning)."""
    from tpurpc.rpc.resolver import resolve_target

    out = []
    for e in endpoints:
        out.extend(resolve_target(e))
    return out


def _resolve_xds(rest: str):
    """Resolver for ``xds:///service`` (registered below)."""
    service = rest.lstrip("/")
    cfg = load_bootstrap()
    fetch = _fetch_snapshot if _use_ads_lite(cfg) else _fetch_snapshot_v3
    endpoints = fetch(_server_uri(cfg), service, cfg.get("node", {}))
    if not endpoints:
        raise ValueError(f"xds assignment for {service!r} is empty")
    return _normalize(endpoints)


def _install_resolver() -> None:
    from tpurpc.rpc.resolver import register_resolver

    register_resolver("xds", _resolve_xds)


_install_resolver()


class XdsWatcher:
    """Dynamic membership: ADS-lite subscription → update_addresses.

    The eds-policy role (``lb_policy/xds/eds.cc``): every assignment
    change the control plane pushes lands in the channel's composition
    tree via :meth:`Channel.update_addresses` (kept subchannels keep
    their connections). Reconnects with backoff when the control plane
    drops; the channel keeps its LAST applied assignment meanwhile
    (gRPC's xds behavior: no assignment churn on control-plane loss).

    Structurally a sibling of :class:`~tpurpc.rpc.lookaside.
    LookasideWatcher` (same subscribe/stream/apply/backoff skeleton) —
    kept separate because the wires diverge (grpclb speaks
    initial_response + ClientStats load reporting; ADS-lite is
    subscribe→assignments), but fixes to either loop's lifecycle
    handling likely apply to both."""

    def __init__(self, channel, service: str,
                 bootstrap: Optional[dict] = None):
        if getattr(channel, "_addrs", None) is None:
            raise ValueError(
                "xds watching needs a target-built channel "
                "(endpoint_factory channels have fixed membership)")
        self._channel = channel
        self._service = service
        self._cfg = bootstrap or load_bootstrap()
        self._stop = threading.Event()
        #: last NORMALIZED assignment applied (seeded from the channel's
        #: current membership): identical pushes — including the control
        #: plane's initial resend of the snapshot the resolver already
        #: fetched — are skipped, so a static assignment never churns the
        #: LB policy or disqualifies the channel's native fast path
        self._last_applied = list(channel._addrs)
        self.applied_versions: List[int] = []  # observability/test seam
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpurpc-xds")
        self._thread.start()

    def _run(self) -> None:
        run = (self._run_lite if _use_ads_lite(self._cfg)
               else self._run_v3)
        uri = _server_uri(self._cfg)
        backoff = 0.2
        while not self._stop.is_set():
            # _healthy: the stream delivered at least one response this
            # connection — reset the reconnect backoff EVEN when the stream
            # later dies by exception (a plane that served for hours then
            # dropped deserves a fast re-dial, not the escalated backoff)
            self._healthy = False
            try:
                run(uri)
            except Exception:
                if self._stop.is_set():
                    return
            if self._healthy:
                backoff = 0.2
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, 5.0)

    def _apply(self, endpoints, version: int) -> None:
        """One keep-the-last-good application unit: normalization may
        raise (bad host:port strings) and must NOT tear the stream down —
        a control plane resending one malformed assignment must not put
        the watcher in a reconnect loop."""
        try:
            addrs = _normalize(list(endpoints))
        except (ValueError, KeyError):
            return
        if addrs and addrs != self._last_applied:
            self._channel.update_addresses(addrs)
            self._last_applied = addrs
            self.applied_versions.append(version)

    def _run_v3(self, uri: str) -> None:
        """The real wire: v3 ADS subscribe → responses → ACK each one
        (version_info + response_nonce echoed, the A* protocols' ACK
        contract) → apply assignments. A response that does not DECODE at
        all is skipped without ACK (its nonce is unreadable, so no NACK is
        possible either); a decodable response is always ACKed, even when
        its assignment is unusable — keep-the-last-good without stalling
        an ACK-gated control plane."""
        import queue as _queue

        from tpurpc.rpc import xds_v3
        from tpurpc.rpc.channel import Channel

        node = self._cfg.get("node", {})
        node_id = str(node.get("id", ""))
        with Channel(uri, connect_timeout=10.0) as bch:
            self._bch = bch  # stop() closes it to unblock the recv
            acks: "_queue.Queue[bytes]" = _queue.Queue()

            def reqs():
                yield xds_v3.encode_discovery_request(
                    [self._service], node_id=node_id,
                    node_cluster=str(node.get("cluster", "")))
                while not self._stop.is_set():
                    try:
                        yield acks.get(timeout=0.2)
                    except _queue.Empty:
                        continue

            for msg in bch.stream_stream(xds_v3.METHOD)(reqs(),
                                                        timeout=None):
                if self._stop.is_set():
                    return
                self._healthy = True
                try:
                    upd = xds_v3.decode_discovery_response(bytes(msg))
                except ValueError:
                    continue  # undecodable: no nonce to ACK/NACK with
                acks.put(xds_v3.encode_discovery_request(
                    [self._service], version_info=upd["version_info"],
                    response_nonce=upd["nonce"], node_id=node_id))
                if self._service in upd["assignments"]:
                    try:
                        version = int(upd["version_info"])
                    except ValueError:
                        version = -1
                    self._apply(upd["assignments"][self._service], version)

    def _run_lite(self, uri: str) -> None:
        """Legacy ADS-lite JSON wire (bootstrap server_features
        ["ads_lite"])."""
        from tpurpc.rpc.channel import Channel

        node = self._cfg.get("node", {})
        with Channel(uri, connect_timeout=10.0) as bch:
            self._bch = bch  # stop() closes it to unblock the recv
            sub = json.dumps({"node": node,
                              "resource": self._service}).encode()

            def reqs():
                yield sub
                while not self._stop.wait(0.2):
                    pass

            for msg in bch.stream_stream(METHOD)(reqs(), timeout=None):
                if self._stop.is_set():
                    return
                self._healthy = True  # resets backoff even if we die later
                try:
                    upd = json.loads(bytes(msg).decode())
                    endpoints = list(upd["endpoints"])
                    version = int(upd.get("version", -1))
                except (ValueError, KeyError):
                    continue  # malformed push (incl. version): keep last good
                self._apply(endpoints, version)

    def stop(self) -> None:
        self._stop.set()
        bch = getattr(self, "_bch", None)
        if bch is not None:
            try:
                bch.close()
            except Exception:
                pass
        self._thread.join(timeout=5)


def xds_channel(target: str, bootstrap: Optional[dict] = None, **channel_kw):
    """``xds:///service`` → a channel whose membership tracks the control
    plane. Returns ``(channel, watcher)``; stop the watcher before (or
    with) closing the channel."""
    if not target.startswith("xds:"):
        raise ValueError(f"not an xds target: {target!r}")
    from tpurpc.rpc.channel import Channel

    service = target[4:].lstrip("/")
    cfg = bootstrap or load_bootstrap()
    fetch = _fetch_snapshot if _use_ads_lite(cfg) else _fetch_snapshot_v3
    endpoints = fetch(_server_uri(cfg), service, cfg.get("node", {}))
    if not endpoints:
        raise ValueError(f"xds assignment for {service!r} is empty")
    addrs = _normalize(endpoints)  # same keys update_addresses will produce
    ch = Channel("ipv4:" + ",".join(f"{h}:{p}" for h, p in addrs),
                 lb_policy=channel_kw.pop("lb_policy", "round_robin"),
                 **channel_kw)
    watcher = XdsWatcher(ch, service, bootstrap=cfg)
    return ch, watcher
