"""Client channel: multiplexed calls over one endpoint, with reconnect-on-UNAVAILABLE.

Reference mapping (SURVEY.md §3.2/§3.3):

* ``Channel`` ≈ ``grpc_channel`` + the client_channel filter
  (``ext/filters/client_channel/client_channel.cc``): it owns subchannel
  (re)connection with exponential backoff (``lib/backoff/``), hands calls to a live
  transport, and maps transport failure to ``UNAVAILABLE`` so callers may retry
  (``rdma_bp_posix.cc:86-96`` annotation rule).
* ``_Connection`` ≈ one chttp2 transport instance: a reader thread demuxing frames
  to per-stream state (``chttp2_transport.cc`` read_action_locked), a write path
  serialized by ``FrameWriter`` (write_action), odd client stream ids as in h2.
* The four ``*MultiCallable`` shapes mirror grpcio's public API
  (``src/python/grpcio/grpc/_channel.py``) so porting an app is mechanical.
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time
from typing import (Callable, Iterable, Iterator, List, Optional, Sequence,
                    Tuple, Union)

from tpurpc.analysis.locks import make_condition, make_lock
from tpurpc.core import ctrlring as _ctrl
from tpurpc.core import rendezvous as _rdv
from tpurpc.core.endpoint import Endpoint, EndpointError, connect_endpoint
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _obs_metrics
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc import frame as fr
from tpurpc.rpc.status import (ChannelConnectivity, Deserializer, Metadata,
                               RpcError, Serializer, StatusCode,
                               deserialize as _deserialize,
                               identity_codec as _identity)
from tpurpc.utils.trace import TraceFlag

trace_channel = TraceFlag("channel")

# tpurpc-scope (ISSUE 4): pipelined-client observability. In-flight depth
# is a scrape-time fleet gauge over live PipelinedUnary windows; the two
# latency histograms record once per pipelined call (microseconds) —
# call_us is send→future-resolved, demux_us is the reader-thread hop from
# terminal delivery to future resolution.
_PIPELINES_INFLIGHT = _obs_metrics.fleet("pipeline_inflight",
                                         lambda pl: pl._inflight)
_PIPE_CALL_US = _obs_metrics.histogram("pipeline_call_us", kind="latency")
_PIPE_DEMUX_US = _obs_metrics.histogram("pipeline_demux_us", kind="latency")
#: tpurpc-blackbox (ISSUE 5): per-method client-observed deadline expiries
#: (PipelinedUnary's timer wheel + the blocking unary path both feed it)
_DEADLINE_EXCEEDED = _obs_metrics.labeled_counter("deadline_exceeded",
                                                  ("method",))
# tpurpc-fleet (ISSUE 6): hedging counters + the interned flight tag for
# the hedge emission sites (pure-int plumbing; the `flight` lint rule
# covers this module). The metadata keys mirror tpurpc.rpc.server's
# LOAD_KEY/PUSHBACK_KEY — duplicated literals rather than a server import
# in the client module (test_fleet pins them equal).
_HEDGES_FIRED = _obs_metrics.counter("hedges_fired")
_HEDGES_WON = _obs_metrics.counter("hedges_won")
_HEDGE_TAG = _flight.tag_for("hedge")
_LOAD_KEY = "tpurpc-load"
_PUSHBACK_KEY = "tpurpc-pushback-ms"


def _pushback_s(exc) -> "Optional[float]":
    """Server retry-pushback (``tpurpc-pushback-ms`` trailing metadata on
    an admission rejection) in seconds, or None when absent/junk."""
    try:
        md = exc.trailing_metadata() or ()
    except Exception:
        return None
    for key, value in md:
        if key == _PUSHBACK_KEY:
            try:
                return max(0.0, float(value) / 1000.0)
            except (TypeError, ValueError):
                return None
    return None


class _ClientStream:
    """Per-call state the reader thread feeds and the caller thread drains."""

    def __init__(self, stream_id: int, queue_depth: int = 64):
        self.stream_id = stream_id
        self.events: "queue.Queue[tuple]" = queue.Queue()
        self.initial_metadata: Optional[List[Tuple[str, "str | bytes"]]] = None
        #: fragment assembly — the FrameReader sink appends wire bytes here
        #: directly (single receive-side copy; no per-fragment bytes + join)
        self.assembly = fr.Assembly()
        self.done = False  # trailers or failure delivered
        self.refused = False  # RST|FLAG_REFUSED: admission refusal, replayable
        #: tpurpc-scope: open "wire" span of a traced call (closed at the
        #: terminal event) + the terminal-delivery stamp for demux latency
        self._wire_span = None
        self._t_terminal = 0
        #: pipelined-call completion hook: invoked (on the delivering thread)
        #: AFTER the terminal event is queued — PipelinedUnary resolves its
        #: future here instead of parking a thread on the event queue
        self.on_terminal: Optional[Callable[[], None]] = None
        #: backpressure: bounded count of completed-but-unconsumed response
        #: messages (see _ServerStream._credits for the full rationale);
        #: trailers/failure events bypass — they must never deadlock
        self._credits = threading.BoundedSemaphore(max(1, queue_depth))

    def _acquire_credit(self) -> bool:
        while not self._credits.acquire(timeout=0.25):
            if self.done:
                return False
        return True

    def release_credit(self) -> None:
        try:
            self._credits.release()
        except ValueError:
            pass

    def commit_message(self, more: bool, oversized: bool = False,
                       compressed: bool = False,
                       recv_limit: "Optional[int]" = None,
                       ) -> "Optional[Tuple[StatusCode, str]]":
        """Returns (code, details) when THIS SIDE failed the stream (bad or
        oversized payload) — the caller owes the server an RST so it stops
        streaming into a stream we've already finished locally."""
        if more:
            return None
        if oversized:
            self.assembly.oversized = False
            code, details = (StatusCode.RESOURCE_EXHAUSTED,
                             "received message larger than "
                             "max_receive_message_length")
            self.deliver_failure(code, details)
            return (code, details)
        # take() detaches the storage (consumers may alias it); the Assembly
        # object itself is reusable for the next message.
        if self._acquire_credit():
            body = self.assembly.take()
            if compressed:
                try:
                    # limit enforced POST-decompression (gzip-bomb guard)
                    body = fr.decompress_message(body, recv_limit)
                except fr.DecompressTooLarge as exc:
                    self.deliver_failure(StatusCode.RESOURCE_EXHAUSTED,
                                         str(exc))
                    return (StatusCode.RESOURCE_EXHAUSTED, str(exc))
                except fr.FrameError as exc:
                    self.deliver_failure(StatusCode.INTERNAL, str(exc))
                    return (StatusCode.INTERNAL, str(exc))
            self.events.put(("message", body))
        else:
            self.assembly.take()  # stream already finished: drop
        return None

    def commit_external(self, body) -> None:
        """tpurpc-express: a rendezvous'd response payload — already whole,
        already in its final resting buffer (the landing region the decode
        will alias). Same credit backpressure as framed commits."""
        if self._acquire_credit():
            self.events.put(("message", body))

    def deliver_trailers(self, code: StatusCode, details: str, md) -> None:
        self.done = True
        self.events.put(("trailers", code, details, md))
        self._fire_terminal()

    def deliver_failure(self, code: StatusCode, details: str) -> None:
        self.done = True
        self.events.put(("trailers", code, details, []))
        self._fire_terminal()

    def _fire_terminal(self) -> None:
        sp = self._wire_span
        if sp is not None:
            self._wire_span = None
            _tracing.finish(sp)
        self._t_terminal = time.perf_counter_ns()
        cb = self.on_terminal
        if cb is not None:
            try:
                cb()
            except Exception:  # a completion hook bug must not kill the
                pass           # reader thread (every stream rides it)


class _ChannelSink(fr.MessageSink):
    """Routes MESSAGE payload bytes into per-stream assembly buffers."""

    def __init__(self, conn: "_Connection"):
        self._conn = conn
        self._discard = fr.Assembly()  # sink for late frames of dead streams

    def buffer_for(self, stream_id: int) -> fr.Assembly:
        with self._conn._lock:
            st = self._conn._streams.get(stream_id)
        if st is None:
            self._discard.take()  # drop late bytes
            return self._discard
        return st.assembly

    def commit(self, stream_id: int, flags: int) -> None:
        with self._conn._lock:
            st = self._conn._streams.get(stream_id)
        if st is not None:
            failed = st.commit_message(
                bool(flags & fr.FLAG_MORE),
                oversized=st.assembly.oversized,
                compressed=bool(flags & fr.FLAG_COMPRESSED),
                recv_limit=self.max_message_bytes)
            if failed is not None:
                # Stream finished locally (undecodable/oversized payload):
                # RST so the server stops streaming into it, and drop the
                # local stream entry so late frames go to the discard sink.
                code, details = failed
                try:
                    self._conn.writer.send(fr.RST, 0, stream_id,
                                           fr.rst_payload(code, details))
                except (EndpointError, OSError):
                    pass
                self._conn.close_stream(st)


class _Connection:
    """One live transport: endpoint + reader thread + muxed writer."""

    def __init__(self, endpoint: Endpoint, on_dead: Callable[["_Connection"], None],
                 max_recv_bytes: "Optional[int]" = None):
        self.endpoint = endpoint
        self.writer = fr.FrameWriter(endpoint)
        self.reader = fr.FrameReader(endpoint)
        self.reader.sink = _ChannelSink(self)
        self.reader.sink.max_message_bytes = max_recv_bytes
        self._streams: dict[int, _ClientStream] = {}
        self._lock = make_lock("_Connection._lock")
        self._next_stream_id = 1  # odd ids, client-initiated (h2 convention)
        self._pong_waiters: List[threading.Event] = []
        self.pong_count = 0  # keepalive verdict ticks compare against this
        self.alive = True
        self.draining = False        # GOAWAY received: no new streams
        self.last_activity = time.monotonic()
        self._on_dead = on_dead
        #: tpurpc-fleet: sink for server load reports stripped from
        #: trailing metadata (bound per pick by Channel._connection when
        #: the LB policy consumes them; None otherwise)
        self.on_load = None
        #: tpurpc-blackbox: connection lifecycle in the flight ring — the
        #: disconnect→reconnect→first-OK sequence a postmortem replays
        self._ftag = _flight.tag_for("conn:" + getattr(endpoint, "peer",
                                                       "?"))
        self._flight_first_ok = False
        _flight.emit(_flight.CONN_CONNECT, self._ftag)
        self.writer.send_preface()
        # tpurpc-express: arm the rendezvous link and say hello. The hello
        # is a PING any peer (native C plane, older builds) safely echoes;
        # only a rendezvous-capable peer recognizes it and replies with its
        # own, which flips `negotiated` — until then every payload frames.
        # tpurpc-pulse (ISSUE 13): the hello also carries this side's
        # descriptor-ring blob; a peer that opens it (same host, shm) moves
        # the whole control plane off frames.
        self.rdv = _rdv.link_for_endpoint(
            endpoint, "chan:" + getattr(endpoint, "peer", "?"),
            self._rdv_send_op, self._rdv_deliver,
            send_ops=self._rdv_send_ops)
        self.writer.rdv = self.rdv
        self._frames_dispatched = 0
        self.ctrl = None
        if self.rdv is not None and _ctrl.enabled():
            try:
                self.ctrl = _ctrl.CtrlPlane(
                    "chan:" + getattr(endpoint, "peer", "?"))
            except Exception:
                self.ctrl = None  # no shm: framed control forever
            if self.ctrl is not None:
                self.rdv.ctrl_post = self._rdv_ctrl_post
                self.rdv.ctrl_drain = self._ctrl_drain
                # per-stream order across the ring/framed split: control
                # ops posted before a sink-routed MESSAGE deliver first
                self.reader.pre_commit = self._ctrl_drain
        if self.rdv is not None:
            self.rdv.recv_limit = max_recv_bytes
            # ring planes negotiated at the PAIR BOOTSTRAP (Address.caps
            # "rdv"): arm immediately — no hello round trip for the first
            # bulk payload to race
            pair = getattr(endpoint, "pair", None)
            if pair is not None and "rdv" in getattr(pair, "peer_caps",
                                                     ()):
                self.rdv.on_peer_hello()
            hello = _rdv.HELLO_PAYLOAD
            if self.ctrl is not None:
                hello += self.ctrl.hello_blob()
            try:
                self.writer.send(fr.PING, 0, 0, hello)
            except (EndpointError, OSError, fr.FrameError):
                pass  # connection dying; normal paths surface it
        # Inline-pump discipline (the reference's pollset_work model,
        # SURVEY §3.4; the Python analog of TPURPC_NATIVE_INLINE_READ):
        # on ring platforms the WAITING CALLER pumps the transport itself,
        # eliminating the reader-thread→caller wakeup from every RTT — on
        # the 1-core bench host those 2 extra context switches per round
        # trip were why the Python ring path LOST to TCP (VERDICT r3 weak
        # #4). TPURPC_INLINE_PUMP=auto (default) enables it for ring
        # endpoints; =1 forces it for every endpoint; =0 keeps the
        # dedicated reader thread everywhere.
        self._pump_mode = self._pump_enabled(endpoint)
        self._pumping = False
        self._pump_cond = make_condition("_Connection._pump_cond", self._lock)
        if self.rdv is not None and self._pump_mode:
            # inline-pump transports: a sender waiting for a CLAIM must
            # drive the reader itself (nobody else will) — hand the link
            # the pump-wait primitive instead of its condition fallback
            self.rdv._pump = self._pump_wait
        if self._pump_mode:
            self._start_backup_pump()
        else:
            self._thread = threading.Thread(target=self._read_loop,
                                            daemon=True,
                                            name="tpurpc-chan-reader")
            self._thread.start()
        self._start_keepalive()
        self._start_idle_monitor()

    @staticmethod
    def _pump_enabled(endpoint: Endpoint) -> bool:
        mode = os.environ.get("TPURPC_INLINE_PUMP", "auto").lower()
        if mode in ("0", "off", "false"):
            return False
        if mode in ("1", "on", "true"):
            return True
        # auto: ring endpoints only (a Pair-backed byte pipe — the path the
        # discipline was built for; TCP keeps the blocking reader thread)
        return hasattr(endpoint, "pair")

    def _start_keepalive(self) -> None:
        """Client keepalive (GRPC_ARG_KEEPALIVE_TIME_MS family, off by
        default like gRPC): PING on an idle cadence; a missed PONG within
        keepalive_timeout kills the connection so the channel's reconnect
        machinery takes over instead of calls hanging on a dead peer.

        Runs on the shared timer wheel, event-style (the reference drives
        keepalive from iomgr timers the same way): one tick sends the PING
        and schedules a verdict tick that compares pong_count — no blocking
        ping() on the wheel thread, and no dedicated thread per connection
        (a thread per connection was 2x128 threads at the reference's
        128-client scale)."""
        from tpurpc.utils.config import get_config
        from tpurpc.utils.timers import schedule

        cfg = get_config()
        if cfg.keepalive_time_ms <= 0:
            return
        interval = cfg.keepalive_time_ms / 1000.0
        timeout = max(0.001, cfg.keepalive_timeout_ms / 1000.0)

        from tpurpc.utils.timers import run_blocking

        def tick():
            if not self.alive:
                return
            # Ping only a genuinely idle connection (gRPC pings after
            # keepalive_time of *inactivity*; the server loop skips
            # in-flight streams for the same reason): with streams open,
            # the single reader thread can be parked in credit-acquire or
            # a long message burst, leaving the PONG unread past the
            # timeout — and the keepalive would then kill a healthy
            # connection, failing every in-flight call UNAVAILABLE.
            with self._lock:
                busy = (bool(self._streams)
                        or time.monotonic() - self.last_activity < interval)
                before = self.pong_count
            if busy:
                self._ka_handle = schedule(interval, tick)
                return
            sent_at = time.monotonic()

            def send_ping():  # endpoint write: never on the wheel thread
                try:
                    self.writer.send(fr.PING, 0, 0, b"tpurpc-keepalive")
                except (EndpointError, OSError, fr.FrameError):
                    self._die("keepalive ping send failed")

            run_blocking(send_ping)

            def check():
                # Sliced verdict: answered → next PING an INTERVAL after
                # this one (the configured cadence; waiting the full
                # timeout first would stretch it to interval+timeout);
                # unanswered past timeout → reap, off-wheel (teardown
                # closes fds / fails streams).
                if not self.alive:
                    return
                elapsed = time.monotonic() - sent_at
                with self._lock:
                    ponged = self.pong_count > before
                if ponged:
                    self._ka_handle = schedule(max(0.05, interval - elapsed),
                                               tick)
                elif elapsed >= timeout:
                    run_blocking(
                        lambda: self._die("keepalive ping timed out"))
                else:
                    self._ka_handle = schedule(
                        min(1.0, max(0.05, timeout - elapsed)), check)

            self._ka_handle = schedule(min(1.0, timeout), check)

        self._ka_handle = schedule(interval, tick)

    def _start_idle_monitor(self) -> None:
        """client_idle filter analog (GRPC_ARG_CLIENT_IDLE_TIMEOUT_MS, off
        by default): a connection with no streams and no activity for the
        idle window is closed; the next call dials fresh. Frees server-side
        per-connection state (pairs, rings) held by forgotten channels.
        Wheel-scheduled checks — no per-connection thread."""
        from tpurpc.utils.config import get_config
        from tpurpc.utils.timers import schedule

        cfg = get_config()
        if cfg.client_idle_timeout_ms <= 0:
            return
        window = cfg.client_idle_timeout_ms / 1000.0

        def tick():
            if not self.alive:
                return
            with self._lock:
                remain = window - (time.monotonic() - self.last_activity)
                busy = bool(self._streams)
                idle = not busy and remain <= 0
                if idle:
                    # Gate BEFORE releasing the lock: open_stream checks
                    # draining under this same lock, so a call racing
                    # the idle close gets "draining" (transparently
                    # re-dialed) instead of a spurious UNAVAILABLE
                    # after its HEADERS hit a dying connection.
                    self.draining = True
                # streams in flight: re-check a full window from now;
                # otherwise wake exactly when the idle window would lapse
                delay = window if busy else max(0.05, remain)
            if idle:
                from tpurpc.utils.timers import run_blocking

                run_blocking(lambda: self._die("client idle timeout"))
                return
            self._idle_handle = schedule(delay, tick)

        self._idle_handle = schedule(window, tick)

    def open_stream(self) -> _ClientStream:
        with self._lock:
            if not self.alive:
                raise EndpointError("connection closed")
            if self.draining:
                raise EndpointError("connection draining (GOAWAY)")
            sid = self._next_stream_id
            self._next_stream_id += 2
            from tpurpc.utils.config import get_config

            st = _ClientStream(sid,
                               queue_depth=get_config().stream_queue_depth)
            self._streams[sid] = st
            self.last_activity = time.monotonic()
            return st

    def close_stream(self, st: _ClientStream) -> None:
        finish_drain = False
        with self._lock:
            self._streams.pop(st.stream_id, None)
            self.last_activity = time.monotonic()
            finish_drain = self.draining and not self._streams
        if finish_drain:
            # last in-flight call on a GOAWAY'd connection finished: the
            # graceful close completes (max_connection_age contract)
            self._die("drained after GOAWAY")

    def _read_loop(self) -> None:
        try:
            while True:
                f = self._read_frame_ctrl()
                if f is None:
                    self._die("server closed connection")
                    return
                if f is fr.CONSUMED:  # MESSAGE already routed via the sink
                    self._frames_dispatched += 1
                    continue
                self._dispatch(f)
                self._frames_dispatched += 1
        except (EndpointError, fr.FrameError, OSError) as exc:
            self._die(str(exc))

    # -- inline pump (pump-mode connections only) -----------------------------

    def _pump_wait(self, pred: Callable[[], bool],
                   deadline: Optional[float]) -> bool:
        """Wait for ``pred`` by PUMPING the transport from this thread.

        One pumper at a time owns the FrameReader (it is not thread-safe);
        others park on the condition and are notified after every dispatched
        frame, so a parked waiter whose pred was satisfied by the owner's
        pumping wakes immediately — the owner keeps pumping only until its
        OWN pred holds (native analog: tpurpc_client.cc pump_until).

        Returns True when pred() holds or the connection died (the caller
        decodes the terminal state from its event queue); False only when
        ``deadline`` (a time.monotonic() instant) passed."""
        while True:
            with self._pump_cond:
                while True:
                    if pred() or not self.alive:
                        return True
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        return False
                    if not self._pumping:
                        self._pumping = True
                        break  # this thread owns the pump now
                    self._pump_cond.wait(remaining)
            try:
                self._pump(pred, deadline)
            finally:
                with self._pump_cond:
                    self._pumping = False
                    self._pump_cond.notify_all()
            # loop: re-evaluate pred/deadline under the lock (the pump may
            # have returned because the connection died mid-frame)

    def _pump(self, pred: Callable[[], bool],
              deadline: Optional[float]) -> None:
        """Drain frames until pred/deadline/death. Runs WITHOUT the
        connection lock (the credit-backpressure path inside sink.commit
        may block until a consumer drains its queue; consumers must be able
        to run), owning the reader exclusively via ``_pumping``."""
        while True:
            with self._lock:
                if pred() or not self.alive:
                    return
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                return

            def _stop() -> bool:
                # a ctrl-ring drain inside the polled read may satisfy the
                # pred with no frame ever arriving — bail back to the
                # outer loop instead of blocking out the deadline
                with self._lock:
                    return pred() or not self.alive

            try:
                f = self._read_frame_ctrl(remaining, should_stop=_stop)
            except TimeoutError:
                return  # deadline/pred: outer loop re-checks
            except (EndpointError, fr.FrameError, OSError) as exc:
                self._die(str(exc))
                return
            if f is None:
                self._die("server closed connection")
                return
            if f is not fr.CONSUMED:
                self._dispatch(f)
            self._frames_dispatched += 1
            # every frame (CONSUMED commits included) may satisfy a PARKED
            # waiter's pred — hand them the wakeup now, not at pump release
            with self._pump_cond:
                self._pump_cond.notify_all()

    def _start_backup_pump(self) -> None:
        """Idle servicing for pump-mode connections: with no caller waiting
        (no RPC in flight), nobody pumps — server PINGs, GOAWAYs, and
        keepalive PONGs would sit unread. A timer-wheel tick takes the pump
        when it is free and drains whatever is already buffered. This is
        the backup-poller role gRPC's client runs for the same reason."""
        from tpurpc.utils.config import get_config
        from tpurpc.utils.timers import run_blocking, schedule

        # The backup pump is the only transport reader on an IDLE pump-mode
        # connection, so its cadence must beat the keepalive verdict: a
        # PONG that sits unread past keepalive_timeout would reap a
        # healthy connection. A third of the timeout guarantees >=2 pump
        # chances inside any verdict window.
        cfg = get_config()
        INTERVAL = 0.5
        if cfg.keepalive_time_ms > 0:
            INTERVAL = min(INTERVAL,
                           max(0.05, cfg.keepalive_timeout_ms / 1000.0 / 3))

        def service():
            if not self.alive:
                return
            with self._pump_cond:
                grab = not self._pumping and self.alive
                if grab:
                    self._pumping = True
            if grab:
                try:
                    while True:
                        self._ctrl_drain()
                        try:
                            f = self.reader.read_frame(timeout=0.005)
                        except TimeoutError:
                            break
                        except (EndpointError, fr.FrameError, OSError) as exc:
                            self._die(str(exc))
                            return
                        if f is None:
                            self._die("server closed connection")
                            return
                        if f is not fr.CONSUMED:
                            self._ctrl_drain()  # ring ops sent before f
                            self._dispatch(f)
                        self._frames_dispatched += 1
                finally:
                    with self._pump_cond:
                        self._pumping = False
                        self._pump_cond.notify_all()
            if self.alive:
                self._backup_handle = schedule(INTERVAL, tick)

        def tick():
            run_blocking(service)

        self._backup_handle = schedule(INTERVAL, tick)

    # -- rendezvous plumbing (tpurpc-express) ---------------------------------

    def _rdv_send_op(self, op: int, stream_id: int, payload: bytes) -> None:
        self.writer.send(fr.RDV_FRAME_OF_OP[op], 0, stream_id, payload)

    def _rdv_send_ops(self, ops) -> None:
        """Cold-path coalescer flush: every queued control op in ONE
        gathered writev (tpurpc-pulse)."""
        self.writer.send_many([(fr.RDV_FRAME_OF_OP[op], 0, sid, payload)
                               for op, sid, payload in ops])

    # -- descriptor-ring control plane (tpurpc-pulse, ISSUE 13) ---------------

    def _rdv_ctrl_post(self, op: int, stream_id: int,
                       payload: bytes) -> bool:
        plane = self.ctrl
        if plane is None:
            return False
        return plane.post(op, stream_id, payload, self.writer.frames_sent,
                          self._ctrl_kick)

    def _ctrl_kick(self) -> None:
        try:
            self.writer.send(fr.CTRL_KICK, 0, 0, b"")
        except (EndpointError, OSError, fr.FrameError):
            pass  # connection dying; the framed paths surface it

    def _frames_count(self) -> int:
        return self._frames_dispatched

    def _ctrl_drain(self) -> int:
        plane, rdv = self.ctrl, self.rdv
        if plane is None or rdv is None:
            return 0
        n = plane.drain(rdv.on_op, self._frames_count)
        if n and self._pump_mode:
            # a drained record may satisfy a PARKED pump waiter's pred —
            # same handoff the frame path performs after each dispatch
            with self._pump_cond:
                self._pump_cond.notify_all()
        return n

    def _read_frame_ctrl(self, timeout=None, should_stop=None):
        plane = self.ctrl
        if plane is None or plane.rx is None:
            return self.reader.read_frame(timeout=timeout)
        return _ctrl.read_frame_polled(self.reader.read_frame,
                                       self._ctrl_drain, plane, timeout,
                                       should_stop)

    def _rdv_deliver(self, stream_id: int, flags: int, body) -> None:
        """A completed rendezvous payload IS the stream's next message —
        delivered in frame-arrival order, zero-copy (the body aliases the
        landing region; credits/backpressure identical to framed commits)."""
        with self._lock:
            st = self._streams.get(stream_id)
        if st is not None:
            st.commit_external(body)

    def _dispatch(self, f: fr.Frame) -> None:
        if f.type == fr.PING:
            if (self.rdv is not None
                    and f.payload.startswith(_rdv.HELLO_PAYLOAD)):
                # capability hello: the peer speaks rendezvous (both sides
                # send one proactively at connection start, so no echo).
                # tpurpc-pulse: the tail of the payload is the peer's
                # descriptor-ring blob — adopting it moves this link's
                # control plane off frames entirely.
                self.rdv.on_peer_hello(f.payload)
                if self.ctrl is not None:
                    self.ctrl.on_hello(
                        f.payload[len(_rdv.HELLO_PAYLOAD):])
            self.writer.send(fr.PONG, 0, 0, f.payload)
            return
        if f.type == fr.CTRL_KICK:
            return  # the wake itself was the delivery: read loops drain
        if f.type in fr.RDV_OP_OF_FRAME:
            if self.rdv is not None:
                self.rdv.on_op(fr.RDV_OP_OF_FRAME[f.type], f.stream_id,
                               f.payload)
            return
        if f.type == fr.PONG:
            with self._lock:
                self.pong_count += 1
                waiters, self._pong_waiters = self._pong_waiters, []
            for ev in waiters:
                ev.set()
            return
        if f.type == fr.GOAWAY:
            # Graceful drain (gRPC GOAWAY semantics / max_age filter): stop
            # opening new streams here — the subchannel dials fresh for the
            # next call — but let in-flight calls run to completion. Close
            # when the last one finishes (or now, if none are in flight).
            with self._lock:
                self.draining = True
                empty = not self._streams
            if empty:
                self._die("server sent GOAWAY")
            return
        with self._lock:
            st = self._streams.get(f.stream_id)
        if st is None:
            return  # late frame for a cancelled/finished stream
        if f.type == fr.MESSAGE:  # only without a sink (never in practice)
            st.assembly.append(f.payload)
            st.commit_message(
                bool(f.flags & fr.FLAG_MORE),
                compressed=bool(f.flags & fr.FLAG_COMPRESSED))
        elif f.type == fr.HEADERS:
            md, _ = fr.decode_metadata(f.payload)
            st.initial_metadata = md
            st.events.put(("initial_metadata", md))
        elif f.type in (fr.TRAILERS, fr.RST):
            code, details, md = fr.parse_trailers(f.payload)
            if md:
                # tpurpc-fleet: the server's piggybacked load report is
                # transport-internal — strip it before metadata surfaces
                # to the app, feed it to the LB policy's sink
                for i, (key, value) in enumerate(md):
                    if key == _LOAD_KEY:
                        del md[i]
                        cb = self.on_load
                        if cb is not None:
                            try:
                                cb(value)
                            except Exception:
                                pass  # a policy bug must not kill the reader
                        break
            if f.type == fr.RST and f.flags & fr.FLAG_REFUSED:
                # admission refusal: the server certifies no handler ran
                # (set BEFORE the event lands; the queue orders the read)
                st.refused = True
            # Terminal frame: nothing further arrives for this stream — drop it
            # now so abandoned Call objects don't leak connection state.
            self.close_stream(st)
            st.deliver_trailers(code, details, md)
        else:
            raise fr.FrameError(f"unexpected frame {f!r}")

    def ping(self, timeout: float) -> float:
        """Round-trip one PING/PONG; returns seconds or raises on no reply."""
        ev = threading.Event()
        with self._lock:
            if not self.alive:
                raise EndpointError("connection closed")
            self._pong_waiters.append(ev)
            before = self.pong_count
        t0 = time.perf_counter()
        self.writer.send(fr.PING, 0, 0, b"tpurpc-ping")
        if self._pump_mode:
            ok = self._pump_wait(lambda: self.pong_count > before,
                                 time.monotonic() + timeout)
            if not ok:
                raise TimeoutError("ping timed out")
        elif not ev.wait(timeout):
            raise TimeoutError("ping timed out")
        if not self.alive:  # waiters are released on death too
            raise EndpointError("connection died during ping")
        return time.perf_counter() - t0

    def _die(self, why: str) -> None:
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            streams = list(self._streams.values())
            self._streams.clear()
            waiters, self._pong_waiters = self._pong_waiters, []
        for ev in waiters:
            ev.set()  # ping() observes !alive via the raced send/raise below
        for attr in ("_ka_handle", "_idle_handle", "_backup_handle"):
            h = getattr(self, attr, None)
            if h is not None:
                h.cancel()  # wheel ticks also re-check alive themselves
        graceful = "GOAWAY" in why or "closed" in why or "idle" in why
        _flight.emit(_flight.CONN_DEAD, self._ftag, 1 if graceful else 0)
        if self.rdv is not None:
            # peer gone mid-rendezvous: every claimed landing region is
            # released (the modeled peer-death invariant) and any sender
            # parked on a claim wakes to fall back/fail with the transport
            self.rdv.close()
        if self.ctrl is not None:
            # descriptor rings die with the connection: our rx region is
            # released (a straggling peer's late slot store lands in the
            # orphaned mapping — dead memory, never a re-advertised ring)
            self.ctrl.close()
        trace_channel.log("connection dead: %s", why)
        for st in streams:
            st.deliver_failure(StatusCode.UNAVAILABLE, f"transport failed: {why}")
        try:
            self.endpoint.close()
        except Exception:
            pass
        self._on_dead(self)

    def close(self) -> None:
        self._die("channel closed")


class _Subchannel:
    """One address's connection + exponential reconnect backoff
    (≈ Subchannel in client_channel + lib/backoff, SURVEY.md §3.2)."""

    def __init__(self, factory: Callable[[], Endpoint], channel: "Channel"):
        self._factory = factory
        self._channel = channel
        self._conn: Optional[_Connection] = None
        # guards _conn/backoff state
        self._lock = make_lock("_Subchannel._lock")
        # serializes dial attempts only
        self._connect_lock = make_lock("_Subchannel._connect_lock")
        self._backoff = Channel._BACKOFF_INITIAL
        self._next_attempt = 0.0
        #: tpurpc-blackbox: a previous connection died — the NEXT
        #: successful dial is a reconnect (flight-recorder event)
        self._lost_conn = False

    def get(self, fail_fast: bool = False) -> _Connection:
        """The live connection, dialing if needed. ``fail_fast=True`` (the
        multi-subchannel LB walk) raises UNAVAILABLE immediately while the
        subchannel is in connect backoff instead of sleeping it out —
        sleeping through backoff INSIDE the dial lock convoys every walker
        behind one dead backend (observed: hedged fleet traffic serializing
        2 s per caller on a killed server), and with other backends in the
        walk there is nothing worth waiting for. Single-subchannel channels
        keep the sleep: there, waiting out the backoff IS the reconnect
        contract."""
        with self._lock:
            if (self._conn is not None and self._conn.alive
                    and not self._conn.draining):
                return self._conn
            if fail_fast and self._next_attempt > time.monotonic():
                raise RpcError(StatusCode.UNAVAILABLE,
                               "subchannel in connect backoff")
        # Dial outside self._lock: a blackholed connect must not freeze close()
        # or concurrent calls for the whole connect timeout.
        with self._connect_lock:
            with self._lock:
                if (self._conn is not None and self._conn.alive
                        and not self._conn.draining):
                    return self._conn
                wait = self._next_attempt - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            if self._channel._is_closed():
                raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
            try:
                ep = self._factory()
                conn = _Connection(
                    ep, self._on_conn_dead,
                    max_recv_bytes=self._channel.max_receive_message_length)
            except (OSError, EndpointError) as exc:
                with self._lock:
                    self._next_attempt = (
                        time.monotonic()
                        + self._backoff * (1 + 0.2 * random.random()))
                    self._backoff = min(self._backoff * Channel._BACKOFF_MULT,
                                        Channel._BACKOFF_MAX)
                raise RpcError(StatusCode.UNAVAILABLE,
                               f"connect failed: {exc}") from exc
            with self._lock:
                if self._channel._is_closed():
                    conn.close()
                    raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
                self._backoff = Channel._BACKOFF_INITIAL
                self._conn = conn
                was_lost, self._lost_conn = self._lost_conn, False
            if was_lost:
                _flight.emit(_flight.RECONNECT, conn._ftag)
            return conn

    def _on_conn_dead(self, conn: _Connection) -> None:
        with self._lock:
            if self._conn is conn:
                self._conn = None
            self._lost_conn = True

    def close(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()


class Channel:
    """A lazily-(re)connecting client channel.

    ``target`` is ``"host:port"``; tests may instead inject ``endpoint_factory``
    (e.g. one half of :func:`tpurpc.core.endpoint.passthru_endpoint_pair` — the
    moral equivalent of the reference's inproc transport).

    ``lb_policy`` is a policy name (``"pick_first"``, ``"round_robin"``,
    ``"ring_hash"``) or a composition-tree dict spec (``priority`` /
    ``weighted_target`` over subchannel index subsets) — see
    :func:`tpurpc.rpc.resolver.make_policy` for the grammar.
    """

    #: reconnect backoff, mirroring lib/backoff defaults (initial 1s would be
    #: sluggish for tests; we start at 50ms, cap 2s, jitter 20%).
    _BACKOFF_INITIAL = 0.05
    _BACKOFF_MAX = 2.0
    _BACKOFF_MULT = 1.6

    def __init__(self, target: Optional[str] = None, *,
                 endpoint_factory: Optional[Callable[[], Endpoint]] = None,
                 connect_timeout: float = 30.0,
                 lb_policy: "Union[str, dict]" = "pick_first",
                 credentials=None,
                 max_receive_message_length: Optional[int] = None,
                 retry_policy: "Optional[RetryPolicy]" = None,
                 hedging_policy: "Optional[HedgingPolicy]" = None,
                 compression=None,
                 options=None):
        # grpcio channel options: [("grpc.arg_name", value), ...]. The
        # recognized args map onto this constructor's own parameters (an
        # explicit parameter wins); unrecognized ones are ignored the way
        # grpcio ignores unknown channel args.
        if options:
            opt = dict(options)
            if max_receive_message_length is None:
                max_receive_message_length = opt.get(
                    "grpc.max_receive_message_length")
            if lb_policy == "pick_first" and "grpc.lb_policy_name" in opt:
                lb_policy = opt["grpc.lb_policy_name"]
            if compression is None:
                compression = opt.get("grpc.default_compression_algorithm")
            #: grpcio's service-config channel arg: a JSON FALLBACK used
            #: only when the resolver delivers no config (gRPC documents
            #: GRPC_ARG_SERVICE_CONFIG as ignored when the resolver
            #: returns one; the resolver wins)
            self._svc_cfg_fallback = opt.get("grpc.service_config")
        else:
            self._svc_cfg_fallback = None
        # Message compression on the tpurpc framing (FLAG_COMPRESSED; the
        # h2 wire negotiates grpc-encoding separately): requests compress,
        # tpurpc servers mirror on responses. The framing's one codec is
        # gzip, so grpcio's Compression.Deflate (1) — which a drop-in call
        # site may legitimately pass — is honored as "compress my
        # messages" using that codec rather than rejected at construction.
        # Unknown values degrade to identity with a warning (grpcio
        # tolerates unknown channel args; a constructor ValueError would
        # break drop-in compatibility).
        if compression in (None, 0, "identity", False):
            self._compress_flag = 0
        elif (compression in ("gzip", "deflate", 1, 2)
              or str(compression).endswith(("Gzip", "Deflate"))):
            self._compress_flag = fr.FLAG_COMPRESSED
        else:
            import warnings
            warnings.warn(
                f"unsupported compression {compression!r}: the tpurpc "
                "framing speaks gzip only — using identity", stacklevel=2)
            self._compress_flag = 0
        #: channel-level retry policy for unary-request calls (None = off,
        #: matching gRPC's default of retries disabled without service
        #: config). An explicit policy here WINS over any service config the
        #: resolver delivers (explicit code beats delivered config).
        self.retry_policy = retry_policy
        #: channel-level hedging policy (tpurpc-fleet, gRFC A6): staggered
        #: parallel attempts on distinct subchannels, first response wins.
        #: Retry wins when both are configured (a call runs ONE strategy);
        #: same explicit-beats-config precedence as retry_policy.
        self.hedging_policy = hedging_policy
        #: parsed resolver-delivered service config (per-method timeout /
        #: retryPolicy / retryThrottling — service_config.cc analog); swapped
        #: whole by update_service_config, consulted per call via
        #: _policy_for/_effective_timeout
        self._service_config = None
        from tpurpc.rpc.resolver import make_policy, resolve_target_full
        from tpurpc.utils.config import get_config

        self.max_receive_message_length = get_config().resolve_recv_limit(
            max_receive_message_length)

        ssl_ctx = getattr(credentials, "_context", None)
        override = getattr(credentials, "_override_hostname", None)
        self._lb_spec = lb_policy
        self._conn_kw = dict(timeout=connect_timeout, ssl_context=ssl_ctx,
                             server_hostname=override)
        if endpoint_factory is None:
            if target is None:
                raise ValueError("need target or endpoint_factory")
            resolution = resolve_target_full(target)
            addrs = resolution.addresses
            self._addrs: "Optional[list]" = list(addrs)
            factories = [self._addr_factory(h, p) for h, p in addrs]
            if resolution.service_config is not None:
                self.update_service_config(resolution.service_config)
        else:
            self._addrs = None  # injected factory: membership is fixed
            factories = [endpoint_factory]
        if self._service_config is None and self._svc_cfg_fallback is not None:
            self.update_service_config(self._svc_cfg_fallback)
        self._subchannels = [_Subchannel(f, self) for f in factories]
        self._policy = make_policy(lb_policy, len(self._subchannels))
        self._lock = make_lock("Channel._lock")  # guards _closed
        self._closed = False
        self._kicker: Optional[threading.Thread] = None  # get_state dialer
        # Native unary fast path (lazy; see _native_fast): the reference's
        # defining property is that EVERY binding rides the fast pipe
        # because the hot loop lives in the C core under a thin language
        # surface (grpcio → core, SURVEY §2.4). _native_ch is the cached
        # NativeChannel; _native_retry_at throttles re-dial attempts after
        # a failure so an absent/down native path costs one probe per 5 s.
        self._native_lock = make_lock("Channel._native_lock")
        self._native_ch = None
        self._native_retry_at = 0.0
        from tpurpc.rpc import channelz as _channelz

        #: channelz ChannelData counters (started/succeeded/failed)
        self.call_counters = _channelz.CallCounters()
        _channelz.register_channel(self)

    # -- connection management ----------------------------------------------

    def _addr_factory(self, h: str, p: int):
        kw = self._conn_kw
        return lambda: connect_endpoint(h, p, timeout=kw["timeout"],
                                        ssl_context=kw["ssl_context"],
                                        server_hostname=kw["server_hostname"])

    def update_service_config(self, cfg) -> None:
        """Apply a resolver-delivered JSON service config (dict or JSON
        text): per-method timeouts, retry policies, and channel-wide retry
        throttling take effect for SUBSEQUENT calls without touching call
        sites — the reference's service_config.cc/retry_service_config.cc
        behavior. A malformed config raises and the previous one stays
        (reject-whole, keep-last-good). Retry-throttle DRAIN state carries
        across updates (retry_throttle.cc): a re-resolution re-delivering
        the same config must not refill the bucket and resume a suppressed
        retry storm."""
        from tpurpc.rpc.service_config import ServiceConfig

        new = ServiceConfig.from_json(cfg)
        prev = self._service_config
        if new.retry_throttle is not None:
            new.retry_throttle.carry_from(
                prev.retry_throttle if prev else None)
        self._service_config = new

    def _call_plan(self, method: str, timeout: "Optional[float]",
                   wait_for_ready: bool = False):
        """ONE consistent per-call snapshot of the service-config-derived
        values: ``(retry_policy, timeout, throttle, wait_for_ready,
        hedging_policy)``. Derived from a single read of
        ``_service_config`` so a concurrent resolver update can never pair
        one config's retry policy with another's throttle or timeout.
        Rules: explicit constructor policy wins; config timeout can only
        TIGHTEN the call's (min rule); waitForReady is or-ed with the
        per-call kwarg (gRFC A2: the config enables it, a call-site value
        may also enable it); a method runs ONE execution strategy — when
        both retry and hedging resolve, retry wins (the config layer
        already rejects both in one entry, gRFC A6)."""
        sc = self._service_config
        mc = sc.for_method(method) if sc is not None else None
        policy = self.retry_policy
        if policy is None and mc is not None:
            policy = mc.retry_policy
        hedging = self.hedging_policy
        if hedging is None and mc is not None:
            hedging = mc.hedging_policy
        if policy is not None:
            hedging = None
        if mc is not None and mc.timeout is not None:
            timeout = (mc.timeout if timeout is None
                       else min(timeout, mc.timeout))
        return (policy, timeout,
                sc.retry_throttle if sc is not None else None,
                bool(wait_for_ready) or bool(mc and mc.wait_for_ready),
                hedging)

    def update_addresses(self, addrs) -> None:
        """Replace the channel's backend set (re-resolution / look-aside
        balancing — the grpclb ServerList update, ``grpclb.cc``). Addresses
        present in both old and new sets KEEP their live subchannel (and
        its connection); removed ones are closed; the LB policy is rebuilt
        over the new membership with the channel's original spec.

        ``addrs``: iterable of ``(host, port)`` or ``"host:port"`` strings.
        In-flight calls on kept subchannels are unaffected; calls racing
        the swap may still land on a closing backend once and retry per
        the normal UNAVAILABLE path.
        """
        from tpurpc.rpc.resolver import make_policy, resolve_target

        parsed: list = []
        for a in addrs:
            if isinstance(a, tuple):
                parsed.append(a)
            else:
                # resolve strings the same way the constructor did — the
                # keep-live matching below compares against RESOLVED
                # addresses, so "localhost:p" must normalize to the same
                # keys or a no-op update would tear down live connections
                parsed.extend(resolve_target(a))
        if not parsed:
            raise ValueError("update_addresses needs at least one address")
        # Composite dict specs pin absolute subchannel indices — they can't
        # survive a membership size change. Balanced sets get round_robin,
        # exactly what grpclb runs over its server lists (grpclb.cc).
        spec = (self._lb_spec if isinstance(self._lb_spec, str)
                else "round_robin")
        # Dynamic membership (re-resolution / grpclb server lists) is
        # routing the Python transport owns: the single-address native
        # fast path would pin traffic to the original backend. Disable it
        # for this channel permanently.
        with self._native_lock:
            nch, self._native_ch = self._native_ch, None
            self._native_retry_at = float("inf")
        if nch is not None:
            try:
                nch.close()
            except Exception:
                pass
        with self._lock:
            if self._closed:
                raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
            if self._addrs is None:
                raise RuntimeError(
                    "channel built from endpoint_factory has fixed membership")
            old = {}
            for a, sc in zip(self._addrs, self._subchannels):
                old.setdefault(a, []).append(sc)
            new_subs = []
            for a in parsed:
                bucket = old.get(a)
                if bucket:
                    new_subs.append(bucket.pop(0))  # keep the live conn
                else:
                    new_subs.append(_Subchannel(self._addr_factory(*a), self))
            removed = [sc for bucket in old.values() for sc in bucket]
            policy = make_policy(spec, len(new_subs))
            # atomic swap: _connection() snapshots both attributes
            self._subchannels = new_subs
            self._policy = policy
            self._addrs = list(parsed)
        for sc in removed:
            sc.close()

    def batch_calls(self):
        """tpurpc-pulse (ISSUE 13): batch the fused unary sends THIS
        thread issues inside the block into ONE gathered writev — the
        coalesced control path for bursts of small control RPCs (a
        migration drain's N sequence handoffs flush as one transport
        write instead of one frame pair each).  Pipelined ``call_async``
        inside the block composes naturally: the sends queue, the
        responses demux as usual.  Best-effort: on a channel with no
        dialable connection the block simply runs unbatched (the calls
        themselves will surface the dial failure)."""
        import contextlib

        try:
            conn = self._connection()
        except Exception:
            return contextlib.nullcontext()
        return conn.writer.batch()

    def _connection(self, exclude=None, picked=None) -> _Connection:
        """LB pick: walk subchannels in policy order, first READY/dialable
        wins (client_channel resolver→LB→subchannel flow, SURVEY.md §3.2).

        ``exclude`` (a set of :class:`_Subchannel` objects) deprioritizes
        backends this logical call already used — hedged attempts prefer
        distinct subchannels, and a drain-refused replay migrates instead
        of re-hitting the drainer. Excluded subchannels are appended LAST,
        not dropped: landing on a busy backend beats failing the call when
        nothing else is dialable. ``picked`` (a list, out-param) receives
        the chosen subchannel."""
        with self._lock:
            if self._closed:
                raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
            # snapshot: update_addresses swaps both under this lock, so a
            # pick never mixes one generation's policy with another's subs
            policy, subs = self._policy, self._subchannels
        last_exc: Optional[Exception] = None
        order = list(policy.order())
        if exclude:
            order = ([i for i in order if subs[i] not in exclude]
                     + [i for i in order if subs[i] in exclude])
        fail_fast = len(subs) > 1  # walkers skip backing-off members
        for idx in order:
            sc = subs[idx]
            try:
                conn = sc.get(fail_fast=fail_fast)
            except RpcError as exc:
                policy.failed(idx)
                last_exc = exc
                continue
            policy.connected(idx)
            # tpurpc-fleet: bind the connection's load-report sink to this
            # pick's (policy, index) — rebound every pick so a policy
            # rebuilt by update_addresses never receives stale indices
            if hasattr(policy, "load_report"):
                conn.on_load = (lambda raw, _p=policy, _i=idx:
                                _p.load_report(_i, raw))
            if picked is not None:
                picked.append(sc)
            return conn
        raise last_exc if last_exc is not None else RpcError(
            StatusCode.UNAVAILABLE, "no subchannels")

    def device_ring(self):
        """The live connection's device (HBM) receive ring, or None when the
        transport isn't :class:`tpurpc.tpu.endpoint.TpuRingEndpoint`
        (``GRPC_PLATFORM_TYPE=TPU``). NOTE: this dials/picks a connection;
        to decode a response already in hand, prefer
        :meth:`Call.device_ring`, which is pinned to the connection the
        response arrived on."""
        from tpurpc.core.endpoint import device_ring_of

        return device_ring_of(self._connection().endpoint)

    def ping(self, timeout: float = 5.0) -> float:
        """Round-trip a PING; returns seconds.  Liveness probe (the reference's
        analog: rate-limited ``ibv_query_qp``, ``pair.cc:349-375``)."""
        conn = self._connection()
        try:
            return conn.ping(timeout)
        except TimeoutError as exc:
            raise RpcError(StatusCode.DEADLINE_EXCEEDED, str(exc)) from exc
        except (EndpointError, OSError) as exc:
            raise RpcError(StatusCode.UNAVAILABLE, str(exc)) from exc

    def _is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def get_state(self, try_to_connect: bool = False):
        """grpcio's ``Channel.get_state``: the channel-level connectivity
        summary (connectivity_state.h semantics folded over subchannels).

        READY if any subchannel holds a live connection; CONNECTING while
        a kicked dial is in flight; TRANSIENT_FAILURE if none are live but
        some subchannel is in connect backoff; else IDLE.
        ``try_to_connect=True`` on an idle channel kicks ONE background
        dial sweep over the subchannels (the way grpcio's flag kicks the
        channel, not a fixed address) — repeated polls while it runs keep
        reporting CONNECTING instead of stacking threads."""
        CC = ChannelConnectivity
        with self._lock:
            if self._closed:
                return CC.SHUTDOWN
        with self._native_lock:
            nch = self._native_ch
        if nch is not None and nch._ch:
            # calls are flowing through the native fast path: the channel
            # is READY even though no Python-transport connection exists
            return CC.READY
        now = time.monotonic()
        backing_off = False
        for sc in self._subchannels:
            with sc._lock:
                conn = sc._conn
                if conn is not None and conn.alive and not conn.draining:
                    return CC.READY
                if sc._next_attempt > now:
                    backing_off = True
        with self._lock:
            kicker = self._kicker
            if kicker is not None and kicker.is_alive():
                return CC.CONNECTING  # one dial sweep at a time
            if try_to_connect and self._subchannels:
                self._kicker = threading.Thread(
                    target=self._kick_connect, daemon=True,
                    name="tpurpc-try-connect")
                self._kicker.start()
                return CC.CONNECTING
        return CC.TRANSIENT_FAILURE if backing_off else CC.IDLE

    def _kick_connect(self) -> None:
        # Dial every subchannel until one answers: a dead first address
        # must not mask a live second one (the LB policy would reach it).
        for sc in self._subchannels:
            if self._is_closed():
                return
            try:
                sc.get()
                return
            except RpcError:
                continue  # backoff state answers TRANSIENT_FAILURE

    def wait_for_state_change(self, last_observed_state,
                              timeout: Optional[float] = None) -> bool:
        """Block until ``get_state()`` differs from ``last_observed_state``
        (grpcio's experimental channel-watch shape, polled — this channel
        has no state-subscription machinery to hook)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.get_state() == last_observed_state:
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def close(self) -> None:
        with self._lock:
            self._closed = True
        with self._native_lock:
            nch, self._native_ch = self._native_ch, None
            self._native_retry_at = float("inf")  # closed: never re-dial
        if nch is not None:
            try:
                nch.close()
            except Exception:
                pass
        for sc in self._subchannels:
            sc.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- native unary fast path -----------------------------------------------

    def _native_fast(self):
        """The channel's NativeChannel (calls run inside libtpurpc.so's
        inline-read loop — BASELINE.md: 5.42 µs ring RTT vs ~95 µs for the
        pure-Python path on the same host), or None when ineligible.

        Eligibility is the common drop-in case, checked once: plain single
        address, pick_first with static membership, no TLS, no
        compression, a shm-ring platform (where the pure-Python loop
        measurably loses to kernel TCP — VERDICT r3 weak #4; plain-TCP
        channels keep the Python transport, whose kernel-socket path is
        already competitive and fully introspectable), lib present.
        TPURPC_NATIVE_FAST_UNARY=1 forces it for TCP too; =0 opts out
        entirely; TPURPC_NATIVE=0 disables all native paths. Everything
        else silently stays on the Python transport — same wire, same
        server."""
        with self._native_lock:
            if self._native_ch is not None:
                return self._native_ch
            now = time.monotonic()
            if now < self._native_retry_at or self._closed:
                return None
            self._native_retry_at = now + 5.0  # throttle failed probes
            mode = os.environ.get("TPURPC_NATIVE_FAST_UNARY",
                                  "auto").lower()
            if mode in ("0", "off", "false"):
                self._native_retry_at = float("inf")
                return None
            if (self._compress_flag or self._addrs is None
                    or len(self._addrs) != 1 or self._lb_spec != "pick_first"
                    or self._conn_kw.get("ssl_context") is not None):
                self._native_retry_at = float("inf")
                return None
            from tpurpc.utils.config import get_config

            cfg = get_config()
            ring_ok = (cfg.platform.is_ring and cfg.platform.name != "TPU"
                       and cfg.ring_domain == "shm")
            if not (ring_ok or (mode in ("1", "on", "true")
                                and not cfg.platform.is_ring)):
                self._native_retry_at = float("inf")
                return None
            try:
                from tpurpc.rpc.native_client import NativeChannel

                host, port = self._addrs[0]
                # inline_read: the fast path only issues BLOCKING entries
                # (unary calls + NativeCall streams — the .future() CQ
                # path is never used here), so it takes the lowest-latency
                # discipline: callers pump the ring, no reader-thread
                # wakeup per RTT (the 5.65 vs 7.63 µs rows in BASELINE.md)
                self._native_ch = NativeChannel(
                    host, port, connect_timeout=self._conn_kw["timeout"],
                    inline_read=True)
            except Exception:
                return None  # lib absent/unbuildable or server down: retry in 5s
            return self._native_ch

    def _native_invalidate(self, nch) -> None:
        """Drop a dead fast-path channel; the next eligible call re-dials."""
        with self._native_lock:
            if self._native_ch is nch:
                self._native_ch = None
        try:
            nch.close()
        except Exception:
            pass

    # -- call surface (grpcio-shaped) ----------------------------------------

    # Factories accept (and ignore) the extra kwargs grpcio-generated stubs
    # pass (_registered_method=True since grpcio 1.60) and treat None codecs
    # as identity, grpcio-style — so a stock *_pb2_grpc.FooStub(channel)
    # built against THIS channel works unchanged (mechanical-port claim).

    def unary_unary(self, method: str, request_serializer: Serializer = _identity,
                    response_deserializer: Deserializer = _identity,
                    **_grpcio_kwargs) -> "UnaryUnary":
        return UnaryUnary(self, method, request_serializer or _identity,
                          response_deserializer or _identity,
                          allow_native=_grpcio_kwargs.pop(
                              "tpurpc_native", True))

    def unary_stream(self, method: str, request_serializer: Serializer = _identity,
                     response_deserializer: Deserializer = _identity,
                     **_grpcio_kwargs) -> "UnaryStream":
        return UnaryStream(self, method, request_serializer or _identity,
                           response_deserializer or _identity,
                           allow_native=_grpcio_kwargs.pop(
                               "tpurpc_native", True))

    def stream_unary(self, method: str, request_serializer: Serializer = _identity,
                     response_deserializer: Deserializer = _identity,
                     **_grpcio_kwargs) -> "StreamUnary":
        return StreamUnary(self, method, request_serializer or _identity,
                           response_deserializer or _identity,
                           allow_native=_grpcio_kwargs.pop(
                               "tpurpc_native", True))

    def stream_stream(self, method: str, request_serializer: Serializer = _identity,
                      response_deserializer: Deserializer = _identity,
                      **_grpcio_kwargs) -> "StreamStream":
        return StreamStream(self, method, request_serializer or _identity,
                            response_deserializer or _identity,
                            allow_native=_grpcio_kwargs.pop(
                                "tpurpc_native", True))


class Call:
    """In-flight call handle: response iteration, cancel, metadata accessors."""

    def __init__(self, conn: _Connection, st: _ClientStream,
                 deserializer: Deserializer, deadline: Optional[float],
                 counters=None, channel: "Optional[Channel]" = None):
        self._conn = conn
        self._st = st
        self._deser = deserializer
        self._deadline = deadline
        self._trailing: Optional[Metadata] = None
        self._code: Optional[StatusCode] = None
        self._details = ""
        self._cancelled = False
        self._counters = counters  # channelz ChannelData (counted once)
        self._channel = channel  # for compression degrade on UNIMPLEMENTED

    # -- metadata/status ------------------------------------------------------

    def initial_metadata(self):
        return self._st.initial_metadata or []

    def trailing_metadata(self):
        return self._trailing

    def code(self) -> Optional[StatusCode]:
        return self._code

    def details(self) -> str:
        return self._details

    def cancel(self) -> None:
        if self._code is not None or self._cancelled:
            return
        self._cancelled = True
        try:
            self._conn.writer.send(fr.RST, 0, self._st.stream_id,
                                   fr.rst_payload(StatusCode.CANCELLED,
                                                  "cancelled by client"))
        except (EndpointError, OSError):
            pass
        self._st.deliver_failure(StatusCode.CANCELLED, "cancelled by client")

    def __del__(self):
        # An ABANDONED streaming call (iterator dropped mid-stream without
        # cancel) must not wedge the connection: the server keeps streaming,
        # the stream's credit bound fills, and the reader thread would block
        # in _acquire_credit with nobody left to set `done`. GC-time cancel
        # RSTs the server and delivers the failure that unblocks the reader
        # (grpcio's core does the equivalent via call refcounts).
        try:
            self.cancel()
        except Exception:
            pass  # interpreter teardown: modules may be half-dead

    def time_remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def device_ring(self):
        """Device ring of the connection THIS call ran on (or None off the
        TPU platform) — unlike :meth:`Channel.device_ring`, never dials, so
        it can't pick a different subchannel than the one that carried the
        response."""
        from tpurpc.core.endpoint import device_ring_of

        return device_ring_of(self._conn.endpoint)

    # -- response consumption -------------------------------------------------

    def _next_event(self):
        if self._conn._pump_mode:
            # Inline pump: THIS thread drains the transport until its
            # stream has an event — no reader-thread wakeup in the RTT.
            got = self._conn._pump_wait(
                lambda: not self._st.events.empty(), self._deadline)
            if got:
                try:
                    return self._st.events.get_nowait()
                except queue.Empty:
                    # pred held via `not alive`: the death path delivers the
                    # failure event right after flipping alive — wait for it
                    try:
                        return self._st.events.get(timeout=5)
                    except queue.Empty:
                        raise RpcError(
                            StatusCode.UNAVAILABLE,
                            "connection died without delivering status",
                        ) from None
            self._expire()
            raise RpcError(StatusCode.DEADLINE_EXCEEDED,
                           "deadline exceeded awaiting response") from None
        timeout = self.time_remaining()
        try:
            return self._st.events.get(timeout=timeout)
        except queue.Empty:
            self._expire()
            raise RpcError(StatusCode.DEADLINE_EXCEEDED,
                           "deadline exceeded awaiting response") from None

    def _tail_decide(self, error: bool) -> None:
        """tpurpc-blackbox: the client half of the tail-capture decision —
        commit this call's provisional span tree iff it was slow or
        failed (either endpoint committing promotes the shared trace)."""
        stash = getattr(self._st, "_tail", None)
        if stash is None:
            return
        self._st._tail = None  # decide once per stream
        tctx, t0, method = stash
        _tracing.tail_decide(tctx, time.monotonic_ns() - t0,
                             error=error, method=method)

    def _expire(self) -> None:
        if self._counters is not None:  # counters reconcile: expiry = failed
            self._counters.on_finish(False)
            self._counters = None
        self._code = StatusCode.DEADLINE_EXCEEDED
        self._details = "deadline exceeded"
        stash = getattr(self._st, "_tail", None)
        if stash is not None and stash[2]:
            _DEADLINE_EXCEEDED.labels(stash[2]).inc()
        _flight.emit(_flight.DEADLINE_EXPIRED, self._conn._ftag,
                     self._st.stream_id)
        self._tail_decide(error=True)
        try:
            self._conn.writer.send(fr.RST, 0, self._st.stream_id,
                                   fr.rst_payload(StatusCode.DEADLINE_EXCEEDED,
                                                  "deadline exceeded"))
        except (EndpointError, OSError):
            pass
        self._conn.close_stream(self._st)

    def _finish(self, code: StatusCode, details: str, md) -> None:
        if self._counters is not None:
            self._counters.on_finish(code is StatusCode.OK)
            self._counters = None  # retries/dup events must not double-count
        self._code = code
        self._details = details
        self._trailing = md
        self._tail_decide(error=code is not StatusCode.OK)
        if code is StatusCode.OK and not self._conn._flight_first_ok:
            self._conn._flight_first_ok = True
            _flight.emit(_flight.CALL_FIRST_OK, self._conn._ftag)
        if (self._channel is not None and self._channel._compress_flag
                and code is StatusCode.UNIMPLEMENTED
                and fr.COMPRESSED_UNSUPPORTED_SENTINEL in details):
            # Peer can't decompress: degrade the channel to identity so
            # SUBSEQUENT calls (all four shapes) succeed. The unary path
            # additionally replays this one transparently (_with_call_impl).
            self._channel._compress_flag = 0
        self._conn.close_stream(self._st)

    def messages(self) -> Iterator[object]:
        """Yield deserialized responses until trailers; raise on non-OK."""
        while True:
            ev = self._next_event()
            if ev[0] == "initial_metadata":
                continue
            if ev[0] == "message":
                self._st.release_credit()  # slot freed: reader may refill
                yield _deserialize(self._deser, ev[1])
                continue
            _, code, details, md = ev
            self._finish(code, details, md)
            if code is not StatusCode.OK:
                exc = RpcError(code, details, md)
                if getattr(self._st, "refused", False):
                    exc._tpurpc_refused = True  # replay-safe: FLAG_REFUSED
                raise exc
            return

    def __iter__(self):
        return self.messages()


_NO_REQUEST = object()
#: "no sampling decision was made upstream" sentinel for _start's
#: trace_ctx parameter (None means DECIDED-unsampled — don't redraw)
_TRACE_UNSET = object()


def _status_of(exc: RpcError) -> StatusCode:
    """RpcError's grpcio-style ``code()`` method, tolerant of plain attrs."""
    return exc.code() if callable(exc.code) else exc.code


class RetryPolicy:
    """Client retry policy — the reference inherits gRPC's service-config
    retries (retryPolicy: maxAttempts/backoff/retryableStatusCodes, applied
    in the client_channel filter). tpurpc applies it to unary-request calls
    (the full request is in hand to replay); calls that already delivered a
    response message are never retried, matching the gRPC retry contract.

    >>> ch = Channel(target, retry_policy=RetryPolicy(max_attempts=4))
    """

    __slots__ = ("max_attempts", "initial_backoff", "max_backoff",
                 "backoff_multiplier", "retryable_codes")

    def __init__(self, max_attempts: int = 3, initial_backoff: float = 0.05,
                 max_backoff: float = 1.0, backoff_multiplier: float = 2.0,
                 retryable_codes: Sequence[StatusCode] = (
                     StatusCode.UNAVAILABLE,)):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.backoff_multiplier = backoff_multiplier
        self.retryable_codes = tuple(retryable_codes)

    def next_sleep(self, backoff: float,
                   deadline: Optional[float]) -> Optional[float]:
        """The jittered (±20%, lib/backoff style) clamped sleep for the next
        retry, or None when it would outlive the call deadline."""
        sleep = min(backoff, self.max_backoff)
        sleep *= 1.0 + random.uniform(-0.2, 0.2)
        if deadline is not None and time.monotonic() + sleep >= deadline:
            return None
        return sleep

    def run(self, deadline: Optional[float], attempt_fn, throttle=None):
        """Drive attempt_fn() under this policy. ``throttle`` is the
        channel-wide :class:`~tpurpc.rpc.service_config.RetryThrottle`
        (gRFC A6): retryable failures drain it, successes refill it, and a
        drained bucket suppresses the retry (the failure surfaces) so a
        collapsing backend is not hammered by retry storms."""
        backoff = self.initial_backoff
        attempt = 0
        while True:
            try:
                result = attempt_fn()
            except RpcError as exc:
                attempt += 1
                code = _status_of(exc)
                retryable = code in self.retryable_codes
                if throttle is not None and retryable:
                    throttle.record_failure()
                if (attempt >= self.max_attempts
                        or not retryable
                        or getattr(exc, "_tpurpc_committed", False)
                        or (throttle is not None
                            and not throttle.allow_retry())):
                    raise
                sleep = self.next_sleep(backoff, deadline)
                # tpurpc-fleet: an admission-shedding server names its own
                # backoff (tpurpc-pushback-ms) — honor it as the FLOOR of
                # the retry sleep so a shedding backend isn't re-hammered
                # on the client's (possibly tiny) early-attempt backoff
                pushback = _pushback_s(exc)
                if pushback is not None:
                    sleep = pushback if sleep is None else max(sleep,
                                                               pushback)
                    if (deadline is not None
                            and time.monotonic() + sleep >= deadline):
                        sleep = None
                if sleep is None:
                    raise
                time.sleep(sleep)
                backoff *= self.backoff_multiplier
            else:
                if throttle is not None:
                    throttle.record_success()
                return result


class HedgingPolicy:
    """gRFC A6 hedging: up to ``max_attempts`` copies of one unary call in
    flight, staggered ``hedging_delay`` apart, each preferring a subchannel
    the call hasn't used yet. The first usable response wins and the losers
    are cancelled (RST on their streams); a failure with a status in
    ``non_fatal_codes`` fires the next hedge IMMEDIATELY instead of waiting
    out the delay; any other failure is fatal and resolves the call.

    Hedging trades duplicate work for tail latency — the method must be
    idempotent (two servers may both execute it; that is the contract, not
    a bug). All attempts share ONE deadline budget (the caller's timeout,
    anchored once), the channel-wide :class:`RetryThrottle` gates every
    hedge beyond the first (a collapsing fleet stops receiving hedges the
    same way it stops receiving retries), and a server's admission
    pushback stops further hedging outright.

    >>> ch = Channel(target, lb_policy="round_robin",
    ...              hedging_policy=HedgingPolicy(max_attempts=3,
    ...                                           hedging_delay=0.01))
    """

    __slots__ = ("max_attempts", "hedging_delay", "non_fatal_codes")

    def __init__(self, max_attempts: int = 2, hedging_delay: float = 0.05,
                 non_fatal_codes: Sequence[StatusCode] = (
                     StatusCode.UNAVAILABLE,)):
        if max_attempts < 2:
            raise ValueError("max_attempts must be >= 2")
        if hedging_delay < 0:
            raise ValueError("hedging_delay must be >= 0")
        self.max_attempts = int(max_attempts)
        self.hedging_delay = float(hedging_delay)
        self.non_fatal_codes = tuple(non_fatal_codes)


class _MultiCallable:
    def __init__(self, channel: Channel, method: str,
                 serializer: Serializer, deserializer: Deserializer,
                 allow_native: bool = True):
        self._channel = channel
        self._method = method
        self._ser = serializer
        self._deser = deserializer
        #: tpurpc extension (tpurpc_native=False at the factory): opt a
        #: method out of the native fast paths — e.g. to keep a bulk
        #: stream on the fully instrumented Python plane (copy-ledger
        #: runs). Historical note: rounds 3-4 measured the Python plane
        #: FASTER on multi-MiB payloads (0.43 vs 0.86 GB/s) — that gap
        #: was the notify-token-stealing bug fixed in round 5
        #: (ring_transport.h wait_event); the same A/B now measures the
        #: native loop ~40% ahead (1.20 vs 0.86 GB/s), and it wins
        #: small-RPC latency as before.
        self._allow_native = allow_native

    def _dial(self, wait_for_ready: bool,
              deadline: Optional[float],
              exclude=None, picked=None) -> _Connection:
        """One LB-picked connection. With ``wait_for_ready`` (the grpcio
        per-call flag), a channel in TRANSIENT_FAILURE QUEUES the call —
        keep redialing until the deadline — instead of failing it fast
        (gRPC's wait-for-ready semantics; fail-fast is the default)."""
        if not wait_for_ready:
            return self._channel._connection(exclude=exclude, picked=picked)
        while True:
            try:
                return self._channel._connection(exclude=exclude,
                                                 picked=picked)
            except RpcError as exc:
                if (self._channel._is_closed()
                        or _status_of(exc) is not StatusCode.UNAVAILABLE):
                    raise
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise RpcError(
                        StatusCode.DEADLINE_EXCEEDED,
                        "deadline exceeded waiting for channel readiness",
                    ) from exc
                # Subchannel.get already sleeps through its backoff window;
                # this small sleep only paces the no-deadline case. Known
                # bound: the deadline is checked BETWEEN attempts, so one
                # in-flight connect to a blackholed (SYN-dropped) address
                # can overshoot by up to the channel connect_timeout — the
                # dial itself is not interruptible.
                time.sleep(0.05)

    def _start(self, metadata: Optional[Metadata],
               timeout: Optional[float],
               first_request=_NO_REQUEST,
               wait_for_ready: bool = False,
               trace_ctx=_TRACE_UNSET,
               exclude=None, picked=None,
               ) -> Tuple[_Connection, _ClientStream, Call]:
        """Open a stream and send HEADERS — fused with the first (only)
        MESSAGE when the request is known upfront, so a unary call costs one
        transport write/notify instead of two.

        A connection that turned draining (max_age GOAWAY) between the LB
        pick and open_stream is retried transparently on a fresh dial —
        gRPC's "transparent retry" for streams the application never saw on
        the wire; without it every age expiry has a window of spurious
        UNAVAILABLE."""
        # ONE deadline for the whole call, anchored before the dial: time
        # spent queuing in wait_for_ready counts against the caller's
        # timeout (grpcio semantics) — re-anchoring after the dial would
        # let a late-appearing server nearly double the budget.
        deadline = None if timeout is None else time.monotonic() + timeout
        for _ in range(3):
            conn = self._dial(wait_for_ready, deadline,
                              exclude=exclude, picked=picked)
            try:
                st = conn.open_stream()
                break
            except EndpointError:
                if not conn.draining:
                    raise RpcError(StatusCode.UNAVAILABLE,
                                   "connection closed while starting call")
        else:
            raise RpcError(StatusCode.UNAVAILABLE,
                           "no non-draining connection after 3 dials")
        # tpurpc-scope trace propagation (ISSUE 4): a sampled call carries
        # its context in ordinary metadata; the send interval is the
        # "client-send" span, and the open "wire" span rides the stream
        # until the terminal event closes it on the delivering thread.
        # Callers that already drew the sampling decision (UnaryUnary's
        # native-path gate) pass it via trace_ctx; _TRACE_UNSET means
        # decide here.
        if trace_ctx is _TRACE_UNSET:
            tctx = _tracing.maybe_sample() if _tracing.LIVE else None
        else:
            tctx = trace_ctx
        send_sp = None
        if tctx is not None:
            tctx = tctx.child()  # this call's own span id
            metadata = list(metadata or ())
            metadata.append((_tracing.HEADER, tctx.encode()))
            send_sp = _tracing.begin("client-send", tctx)
            # Open the wire span BEFORE the write: on a loopback transport
            # the server can be parsing HEADERS before send_many returns,
            # and the wire interval must enclose every server-side span.
            st._wire_span = _tracing.begin("wire", tctx)
        # tpurpc-blackbox: what Call needs to make the client-side tail
        # decision (and to label deadline expiries) at terminal time
        st._tail = (tctx, time.monotonic_ns(), self._method)
        try:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            timeout_us = (None if remaining is None
                          else max(0, int(remaining * 1e6)))
            hdr_payload = fr.headers_payload(self._method, metadata or (),
                                             timeout_us)
            with _tracing.use(tctx) if tctx is not None \
                    else _tracing.NULL_CM:
                if first_request is _NO_REQUEST:
                    conn.writer.send(fr.HEADERS, 0, st.stream_id, hdr_payload)
                else:
                    conn.writer.send_many([
                        (fr.HEADERS, 0, st.stream_id, hdr_payload),
                        (fr.MESSAGE,
                         fr.FLAG_END_STREAM | self._channel._compress_flag,
                         st.stream_id, self._ser(first_request)),
                    ])
            if tctx is not None:
                _tracing.finish(send_sp)
                send_sp = None
        except fr.FrameError as exc:
            conn.close_stream(st)
            raise RpcError(StatusCode.RESOURCE_EXHAUSTED, str(exc)) from exc
        except (EndpointError, OSError) as exc:
            raise RpcError(StatusCode.UNAVAILABLE,
                           f"transport failed: {exc}") from exc
        self._channel.call_counters.on_start()
        return conn, st, Call(conn, st, self._deser, deadline,
                              counters=self._channel.call_counters,
                              channel=self._channel)

    def _send_one(self, conn: _Connection, st: _ClientStream, request,
                  end_stream: bool) -> None:
        try:
            flags = ((fr.FLAG_END_STREAM if end_stream else 0)
                     | self._channel._compress_flag)
            conn.writer.send(fr.MESSAGE, flags, st.stream_id,
                             self._ser(request))
        except (EndpointError, OSError) as exc:
            raise RpcError(StatusCode.UNAVAILABLE,
                           f"transport failed: {exc}") from exc

    @staticmethod
    def _instruments_live() -> bool:
        """Measurement honesty, one definition for every call shape: an
        open copy-ledger window or live profiling spans are measuring the
        INSTRUMENTED Python data plane — don't route around the
        instruments."""
        from tpurpc.tpu import ledger as _ledger
        from tpurpc.utils import stats as _stats

        return _ledger.tracking() or _stats.profiling_on()

    def _try_native_stream(self, request_iterator: Iterable,
                           timeout: Optional[float],
                           pre_serialized: bool = False):
        """Shared native-stream entry for the three streaming shapes:
        an eager :class:`_NativeStreamCall` through the channel's fast
        path, or None to use the Python transport (ineligible channel,
        live measurement windows, or a dead cached fast channel — which
        is invalidated so the next call re-dials; nothing was sent, so
        the Python replay is unconditionally safe)."""
        if self._instruments_live():
            return None
        nch = self._channel._native_fast()
        if nch is None:
            return None
        # Native-plane trace propagation (ISSUE 4): a sampled stream call
        # carries its context through tpr_call_start's metadata array —
        # same wire key, same server-side extraction as the Python plane.
        md = None
        if _tracing.LIVE:
            tctx = _tracing.maybe_sample()
            if tctx is not None:
                md = [(_tracing.HEADER, tctx.child().encode())]
        try:
            nc = nch.start_call(self._method, timeout, metadata=md)
        except RpcError:
            self._channel._native_invalidate(nch)
            return None
        ser = (lambda x: x) if pre_serialized else self._ser
        return _NativeStreamCall(self._channel, nc, ser, self._deser,
                                 request_iterator, timeout)

    def _send_stream(self, conn: _Connection, st: _ClientStream,
                     request_iterator: Iterable, call: Call) -> None:
        try:
            for request in request_iterator:
                if st.done:
                    return  # server already terminated the call
                self._send_one(conn, st, request, end_stream=False)
            # Pure half-close marker, NOT an empty message (FLAG_NO_MESSAGE).
            conn.writer.send(fr.MESSAGE,
                             fr.FLAG_END_STREAM | fr.FLAG_NO_MESSAGE,
                             st.stream_id, b"")
        except (RpcError, EndpointError, OSError):
            pass  # reader thread surfaces the transport failure with a status
        except Exception as exc:
            # The *user's* request iterator (or serializer) raised: terminate the
            # stream both ways or the call would hang until its deadline and the
            # server handler would block forever on requests.get().
            try:
                conn.writer.send(fr.RST, 0, st.stream_id,
                                 fr.rst_payload(StatusCode.CANCELLED,
                                                f"request iterator raised: {exc}"))
            except (EndpointError, OSError, fr.FrameError):
                pass
            conn.close_stream(st)
            st.deliver_failure(StatusCode.CANCELLED,
                               f"request iterator raised: {exc!r}")


def _reject_call_credentials(grpcio_kw: dict) -> None:
    """grpcio callers may pass credentials/wait_for_ready/compression per
    call. wait_for_ready is honored (queue instead of fail-fast, see
    _MultiCallable._dial); per-call compression is advisory (use the
    CHANNEL-level compression= knob — FLAG_COMPRESSED on the framing);
    per-call CREDENTIALS are a security feature we must not silently
    drop."""
    if grpcio_kw.get("credentials") is not None:
        raise NotImplementedError(
            "per-call credentials are not supported; use channel credentials")


class UnaryUnary(_MultiCallable):
    #: (NativeChannel, native multicallable) cache — rebuilt when the
    #: channel re-dials its fast path after a failure
    _native_mc: "Optional[tuple]" = None

    def __call__(self, request, timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None, **grpcio_kw):
        _reject_call_credentials(grpcio_kw)
        # Native fast path (the grpcio shape: Python surface, C-core hot
        # loop): plain response-only unary calls with no per-call extras
        # run inside libtpurpc.so's inline-read loop. with_call (needs a
        # Call with trailing metadata), metadata, and wait_for_ready —
        # whether per-call or via the service config — stay on the Python
        # transport (the queue-until-ready dial loop lives there).
        # Sampled (traced) calls stay on the Python transport: the unary
        # native entry has no metadata channel to carry the trace context
        # (NativeCall STREAMS do — _try_native_stream threads it through
        # tpr_call_start). Sampling defaults off, so the common path pays
        # one global load. TAIL-provisional contexts do NOT force the
        # Python path — the 5 µs native loop must not pay the 95 µs plane
        # for a trace that is overwhelmingly about to be dropped; instead
        # _native_call synthesizes a post-hoc span iff the call turns out
        # pathological (client-side-only tree, documented trade).
        tctx = _tracing.maybe_sample() if _tracing.LIVE else None
        plan = self._channel._call_plan(self._method, None)
        if ((tctx is None or getattr(tctx, "provisional", False))
                and self._allow_native and not metadata
                and not grpcio_kw.get("wait_for_ready")
                and not plan[3]
                # hedged calls stay on the Python transport: hedging wants
                # N streams on distinct subchannels + cross-thread cancel,
                # none of which the single-pipe native loop can express
                and plan[4] is None
                and not self._instruments_live()):
            nch = self._channel._native_fast()
            if nch is not None:
                done, resp = self._native_call(nch, request, timeout, tctx)
                if done:
                    return resp
        # the sampling decision rides DOWN the call explicitly (not via
        # ambient TLS): re-deriving it in _start would cost a second
        # sampler draw per call even when tracing never fires
        response, _ = self.with_call(request, timeout=timeout,
                                     metadata=metadata,
                                     _trace_ctx=tctx, **grpcio_kw)
        return response

    def _native_call(self, nch, request, timeout: Optional[float],
                     tctx=None):
        """One unary call inside the native loop. Returns ``(True, resp)``
        or ``(False, None)`` — fall back to the Python transport, allowed
        only for failures that PROVE no handler ran (refused/connect-time),
        so a fallback can never re-execute a committed call.

        ``tctx`` is a tail-capture provisional context: nothing is recorded
        on the fast path; iff the call turns out slow or errored, the trace
        commits and a post-hoc ``native-unary`` span materializes — the
        native plane's bounded-cost tail story."""
        cached = self._native_mc
        if cached is None or cached[0] is not nch:
            cached = (nch, nch.unary_unary(self._method))
            self._native_mc = cached
        mc = cached[1]
        counters = self._channel.call_counters
        policy, timeout, throttle, _, _hedging = self._channel._call_plan(
            self._method, timeout)
        deadline = None if timeout is None else time.monotonic() + timeout

        recv_limit = self._channel.max_receive_message_length

        def attempt():
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            counters.on_start()
            try:
                body = mc(self._ser(request), timeout=remaining)
                if recv_limit is not None and len(body) > recv_limit:
                    # max_receive_message_length parity: the native loop
                    # doesn't enforce it, so the contract holds here (the
                    # bytes crossed the wire, the app never sees them —
                    # grpcio's client behaves the same at this layer)
                    raise RpcError(
                        StatusCode.RESOURCE_EXHAUSTED,
                        "received message larger than "
                        "max_receive_message_length")
            except RpcError:
                counters.on_finish(False)
                raise
            counters.on_finish(True)
            return _deserialize(self._deser, body)

        t0 = time.monotonic_ns() if tctx is not None else 0

        def _tail(error: bool) -> None:
            if tctx is None:
                return
            dur = time.monotonic_ns() - t0
            if _tracing.tail_decide(tctx, dur, error=error,
                                    method=self._method):
                _tracing.record("native-unary", tctx, t0, dur,
                                method=self._method)

        try:
            if policy is None:
                result = attempt()
            else:
                result = policy.run(deadline, attempt, throttle=throttle)
            _tail(error=False)
            return True, result
        except RpcError as exc:
            _tail(error=True)
            if _status_of(exc) is StatusCode.UNAVAILABLE:
                # dead fast-path connection: drop it so the next call
                # re-dials. Fall back to the Python transport (its
                # reconnect machinery) only when the failure provably
                # happened before any handler could run.
                self._channel._native_invalidate(nch)
                # Pre-execution failures only: the native side reports the
                # verdict machine-readably (_tpurpc_preexec, set from
                # tpr_unary_call_ex's preexec out-param or by the ctypes
                # wrapper's own admission refusals) — True means the server
                # never saw a complete request, so the Python transport may
                # safely re-dial and replay. Post-send deaths ("connection
                # lost", tpurpc_client.cc die()) carry False — the handler
                # may have executed and replaying would double-execute; they
                # surface to the caller exactly as the Python transport's
                # mid-call death does. Never match on details wording: the
                # human-readable text is not a contract (ADVICE r4 #2). One
                # compat exception, mirroring the transparent-retry gate
                # below: a pre-round-5 SERVER sends its max_age refusal RST
                # without FLAG_REFUSED, so the wording is the only signal.
                if (getattr(exc, "_tpurpc_preexec", False)
                        or "connection draining" in (exc.details() or "")):
                    return False, None
            raise

    def with_call(self, request, timeout: Optional[float] = None,
                  metadata: Optional[Metadata] = None,
                  _trace_ctx=_TRACE_UNSET, **grpcio_kw):
        from tpurpc.utils import stats as _stats

        if _stats.profiling_on():  # GRPCProfiler span: whole unary call
            with _stats.profile("cli_unary"):
                return self._with_call_impl(request, timeout, metadata,
                                            _trace_ctx=_trace_ctx,
                                            **grpcio_kw)
        return self._with_call_impl(request, timeout, metadata,
                                    _trace_ctx=_trace_ctx, **grpcio_kw)

    def _with_call_impl(self, request, timeout: Optional[float] = None,
                        metadata: Optional[Metadata] = None,
                        _trace_ctx=_TRACE_UNSET, **grpcio_kw):
        _reject_call_credentials(grpcio_kw)
        policy, timeout, throttle, eff_wfr, hedging = \
            self._channel._call_plan(
                self._method, timeout, bool(grpcio_kw.get("wait_for_ready")))
        deadline = None if timeout is None else time.monotonic() + timeout
        if policy is None and hedging is not None:
            return self._hedged_call(request, deadline, metadata, eff_wfr,
                                     hedging, throttle, _trace_ctx)
        #: subchannels that REFUSED this logical call (drain/max-age): the
        #: replay deprioritizes them, so a draining backend's traffic
        #: deterministically migrates instead of re-racing the same GOAWAY
        refused_subs: set = set()

        def attempt():
            # Transparent retry (distinct from RetryPolicy): a stream the
            # server REFUSED at admission — RST "connection draining" from a
            # max_age GOAWAY race — never reached a handler, so replaying it
            # on a fresh connection is always safe (gRPC does the same for
            # GOAWAY-refused streams). Each replay re-derives its budget from
            # the OUTER deadline — a per-attempt re-anchor would extend the
            # caller's wall-clock deadline by up to 3 refused attempts.
            def remaining():
                return (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))

            wfr = eff_wfr
            for _ in range(3):
                try:
                    return self._call_once(request, remaining(), metadata,
                                           wfr, trace_ctx=_trace_ctx,
                                           exclude=refused_subs or None)
                except RpcError as exc:
                    committed = getattr(exc, "_tpurpc_committed", False)
                    # FLAG_REFUSED is the contract; the "connection draining"
                    # wording stays as compat with pre-round-5 servers that
                    # sent the RST without the flag
                    refused = ((getattr(exc, "_tpurpc_refused", False)
                                or (_status_of(exc) is StatusCode.UNAVAILABLE
                                    and "connection draining"
                                    in exc.details()))
                               and not committed)
                    # Compression negotiation by probe: a peer that can't
                    # decompress (the native server/client) rejects the
                    # stream with UNIMPLEMENTED before any handler runs, so
                    # degrading the CHANNEL to identity and replaying is
                    # safe — the grpcio equivalent of the server dropping
                    # the codec from grpc-accept-encoding.
                    # (Call._finish already cleared the channel flag when it
                    # saw this trailer, so don't gate on it still being set.)
                    if (not committed and not refused
                            and _status_of(exc) is StatusCode.UNIMPLEMENTED
                            and fr.COMPRESSED_UNSUPPORTED_SENTINEL
                            in exc.details()):
                        self._channel._compress_flag = 0
                        refused = True
                    if not refused:
                        raise
                    sub = getattr(exc, "_tpurpc_sub", None)
                    if sub is not None:
                        refused_subs.add(sub)
            return self._call_once(request, remaining(), metadata, wfr,
                                   trace_ctx=_trace_ctx,
                                   exclude=refused_subs or None)

        if policy is None:
            return attempt()
        return policy.run(deadline, attempt, throttle=throttle)

    def _hedged_call(self, request, deadline: Optional[float],
                     metadata: Optional[Metadata], wait_for_ready: bool,
                     hp: "HedgingPolicy", throttle, trace_ctx):
        """The gRFC A6 hedging state machine (tpurpc-fleet, ISSUE 6).

        One orchestrating thread (the caller's) drives N attempt threads:

        * attempt 0 launches immediately; attempt k+1 launches when the
          hedging delay lapses with nothing resolved, OR immediately when
          an attempt fails with a non-fatal status;
        * every launch beyond the first consults the channel-wide
          RetryThrottle — a drained bucket stops hedging, so hedges can
          never amplify into the retry storm the throttle exists to stop;
        * admission pushback from any attempt stops further hedging
          outright (the fleet said "back off");
        * the first OK response wins: the losers' streams are RST and
          their Calls observe CANCELLED. A fatal (non-retryable) failure
          resolves the call the same way.

        All attempts share the ONE deadline anchored by the caller; each
        attempt thread carries its own remaining-budget snapshot, so every
        outstanding attempt self-resolves by the deadline and the
        orchestrator's final wait cannot hang."""
        def remaining():
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        results: "queue.Queue[tuple]" = queue.Queue()
        lock = make_lock("HedgeOrchestrator._lock")
        calls: dict = {}       # attempt idx -> live Call (for cancellation)
        used_subs: set = set()  # prefer-distinct exclusion, cross-attempt
        done = [False]

        def on_call_for(idx):
            def on_call(call, sub):
                cancel_now = False
                with lock:
                    calls[idx] = call
                    if sub is not None:
                        used_subs.add(sub)
                    if done[0]:
                        cancel_now = True  # raced the winner: die quietly
                if cancel_now:
                    call.cancel()
            return on_call

        def run_attempt(idx):
            refused_local: set = set()
            last_exc = None
            for _ in range(3):  # transparent refused-replay, per attempt
                with lock:
                    excl = set(used_subs) | refused_local
                try:
                    resp, call = self._call_once(
                        request, remaining(), metadata, wait_for_ready,
                        trace_ctx=trace_ctx, exclude=excl or None,
                        on_call=on_call_for(idx))
                    results.put((idx, (resp, call), None))
                    return
                except RpcError as exc:
                    last_exc = exc
                    if (getattr(exc, "_tpurpc_refused", False)
                            and not getattr(exc, "_tpurpc_committed",
                                            False)):
                        sub = getattr(exc, "_tpurpc_sub", None)
                        if sub is not None:
                            refused_local.add(sub)
                        continue
                    results.put((idx, None, exc))
                    return
                except BaseException as exc:  # serializer bug etc.
                    results.put((idx, None, exc))
                    return
            results.put((idx, None, last_exc))

        launched = 0
        outstanding = 0
        stop_hedging = False  # flipped by admission pushback

        def may_hedge():
            return (launched < hp.max_attempts and not stop_hedging
                    and (throttle is None or throttle.allow_retry()))

        def launch():
            nonlocal launched, outstanding
            idx = launched
            launched += 1
            outstanding += 1
            if idx > 0:
                _HEDGES_FIRED.inc()
                _flight.emit(_flight.HEDGE_FIRED, _HEDGE_TAG, idx)
            threading.Thread(target=run_attempt, args=(idx,), daemon=True,
                             name="tpurpc-hedge").start()

        def finish(win_idx=None):
            with lock:
                done[0] = True
                losers = [(i, c) for i, c in calls.items() if i != win_idx]
            for i, call in losers:
                try:
                    call.cancel()
                except Exception:
                    pass
                if win_idx is not None:
                    _flight.emit(_flight.HEDGE_CANCELLED, _HEDGE_TAG, i)

        launch()
        last_failure = None
        while True:
            wait = hp.hedging_delay if may_hedge() else None
            rem = remaining()
            if rem is not None and (wait is None or rem < wait):
                # bound the wait by the budget + slack: outstanding
                # attempts self-expire at the deadline and deliver here
                wait = rem + 1.0
            try:
                idx, ok, exc = results.get(timeout=wait)
            except queue.Empty:
                if may_hedge():
                    launch()  # the delay lapsed unresolved: hedge
                    continue
                if outstanding > 0:
                    continue  # just wait: attempts carry their own deadline
                # nothing in flight, nothing launchable
                finish()
                raise last_failure if last_failure is not None else RpcError(
                    StatusCode.DEADLINE_EXCEEDED,
                    "deadline exceeded before any hedged attempt resolved")
            outstanding -= 1
            if exc is None:
                resp, call = ok
                if idx > 0:
                    _HEDGES_WON.inc()
                _flight.emit(_flight.HEDGE_WON, _HEDGE_TAG, idx)
                finish(win_idx=idx)
                if throttle is not None:
                    throttle.record_success()
                return resp, call
            if done[0]:
                continue  # a cancelled loser reporting in: ignore
            if isinstance(exc, RpcError):
                code = _status_of(exc)
                retryable = (code in hp.non_fatal_codes
                             and not getattr(exc, "_tpurpc_committed",
                                             False))
                if throttle is not None and retryable:
                    throttle.record_failure()
                if _pushback_s(exc) is not None:
                    stop_hedging = True  # the fleet is shedding: no more
                if retryable:
                    last_failure = exc
                    if may_hedge():
                        launch()  # gRFC A6: non-fatal fires the next
                        continue  # hedge immediately
                    if outstanding > 0:
                        continue
                    finish()
                    raise exc
            # fatal failure (or a non-RpcError bug): resolve now
            finish()
            raise exc

    def _call_once(self, request, timeout: Optional[float],
                   metadata: Optional[Metadata], wait_for_ready: bool = False,
                   trace_ctx=_TRACE_UNSET, exclude=None, on_call=None):
        """One wire attempt. ``exclude`` deprioritizes subchannels this
        logical call already touched (drain migration / hedge spread);
        ``on_call(call, subchannel)`` fires as soon as the stream is open —
        the hedged driver registers the Call for cross-attempt
        cancellation there. A failure carries the subchannel it ran on as
        ``_tpurpc_sub`` so callers can extend their exclusion set."""
        picked: list = []
        conn, st, call = self._start(metadata, timeout, first_request=request,
                                     wait_for_ready=wait_for_ready,
                                     trace_ctx=trace_ctx,
                                     exclude=exclude, picked=picked)
        if on_call is not None:
            on_call(call, picked[-1] if picked else None)
        response = None
        got = False
        try:
            for msg in call.messages():
                if got:
                    raise RpcError(StatusCode.INTERNAL,
                                   "unary call received multiple responses")
                response, got = msg, True
        except RpcError as exc:
            if got:
                # A response message was already delivered: the call is
                # committed — replaying it would re-execute the handler
                # (gRPC's retry contract forbids this too).
                exc._tpurpc_committed = True
            if picked:
                exc._tpurpc_sub = picked[-1]
            raise
        if not got:
            raise RpcError(StatusCode.INTERNAL, "unary call received no response")
        return response, call

    def future(self, request, timeout: Optional[float] = None,
               metadata: Optional[Metadata] = None):
        """Minimal future: runs the call on a daemon thread. The caller's
        ring_hash key (a thread-local) is captured NOW and re-installed in
        the worker thread, so keyed routing survives the thread hop."""
        import concurrent.futures

        from tpurpc.rpc import resolver as _resolver

        key = getattr(_resolver._call_key, "key", None)
        fut: "concurrent.futures.Future" = concurrent.futures.Future()

        def run():
            if not fut.set_running_or_notify_cancel():
                return
            try:
                if key is not None:
                    with _resolver.ring_hash_key(key):
                        fut.set_result(self(request, timeout, metadata))
                else:
                    fut.set_result(self(request, timeout, metadata))
            except BaseException as exc:
                fut.set_exception(exc)

        threading.Thread(target=run, daemon=True,
                         name="tpurpc-unary-future").start()
        return fut

    def pipeline(self, depth: int = 16) -> "PipelinedUnary":
        """A bounded-window pipelined caller for this method: many unary
        calls in flight on ONE connection, demuxed by stream id — no
        thread per call (contrast :meth:`future`, which spawns one)."""
        return PipelinedUnary(self, depth=depth)


class PipelinedUnary:
    """Multi-in-flight unary calls over one connection (the serving
    pipeline's client half, ISSUE 3).

    ``call_async`` sends the fused HEADERS+MESSAGE immediately and returns
    a ``concurrent.futures.Future``; the connection's reader (or inline
    pump) thread demuxes completions by stream id and resolves each future
    in place, so N in-flight calls cost N streams — not N parked threads.
    The bounded window (``depth``) backpressures callers: the depth+1'th
    ``call_async`` blocks until a completion frees a slot, which is what
    keeps a fast client from ballooning server-side queues.

    Completion (including response deserialization) runs on the delivering
    thread — keep deserializers cheap (the tensor codec's zero-copy decode
    qualifies). Out-of-order completion across streams is the point: a
    slow call does not head-of-line-block its siblings' futures.
    """

    def __init__(self, mc: "UnaryUnary", depth: int = 16):
        import concurrent.futures

        self._Future = concurrent.futures.Future
        self._mc = mc
        self.depth = max(1, int(depth))
        self._window = threading.BoundedSemaphore(self.depth)
        self._lock = make_lock("PipelinedUnary._lock")
        self._inflight = 0
        self._closed = False
        self._pump_threads: dict = {}  # conn id -> Thread (pump-mode only)
        _PIPELINES_INFLIGHT.track(self)

    def call_async(self, request, timeout: Optional[float] = None,
                   metadata: Optional[Metadata] = None):
        """One pipelined call; returns a Future of the deserialized
        response. Blocks only for a window slot (backpressure), never for
        the response.

        tpurpc-fleet: a REFUSED terminal (drain / max-age GOAWAY race —
        the server certifies no handler ran) replays transparently on
        another subchannel instead of failing the future, up to 3 times
        under the original deadline — the pipelined half of the
        zero-failed-RPC drain contract. The replay's dial runs off the
        delivering reader thread (timer-wheel blocking pool)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._window.acquire(
                timeout=None if timeout is None else timeout):
            raise RpcError(StatusCode.DEADLINE_EXCEEDED,
                           "deadline exceeded waiting for pipeline window")
        t_start = time.perf_counter_ns()
        fut = self._Future()
        state = {"claimed": False, "timer": None, "replays": 0,
                 "exclude": set(), "cur": None}
        # tpurpc-blackbox: register with the stall watchdog — a pipelined
        # call has NO thread parked on it, so the sweeper is the only
        # observer that can notice it wedged and name the stage
        from tpurpc.obs import watchdog as _watchdog

        def claim() -> bool:
            with self._lock:
                if state["claimed"]:
                    return False
                state["claimed"] = True
                self._inflight -= 1
            self._window.release()
            return True

        def start_attempt():
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            picked: list = []
            conn, st, call = self._mc._start(
                metadata, remaining, first_request=request,
                exclude=state["exclude"] or None, picked=picked)
            stash = getattr(st, "_tail", None)
            wd_tok = _watchdog.call_started(
                self._mc._method,
                stash[0].trace_id if stash and stash[0] is not None else 0,
                kind="client")
            cur = {"st": st, "call": call, "wd": wd_tok, "fired": False,
                   "sub": picked[-1] if picked else None}
            state["cur"] = cur

            def complete():
                with self._lock:
                    if cur["fired"]:
                        return  # hook + done-fallback both ran: once only
                    cur["fired"] = True
                msgs = []
                code, details, md = None, "", []
                while True:
                    try:
                        ev = st.events.get_nowait()
                    except queue.Empty:
                        break
                    if ev[0] == "message":
                        st.release_credit()
                        msgs.append(ev[1])
                    elif ev[0] == "trailers":
                        _, code, details, md = ev
                if code is None:  # terminal hook without a queued trailer
                    code, details = (StatusCode.INTERNAL,
                                     "terminal without status")
                refused = (code is not StatusCode.OK and not msgs
                           and getattr(st, "refused", False))
                if refused and state["replays"] < 3 and not state["claimed"]:
                    # migrate: the refusing subchannel is deprioritized and
                    # the attempt replays — off this (reader) thread, which
                    # must not block in a dial
                    state["replays"] += 1
                    if cur["sub"] is not None:
                        state["exclude"].add(cur["sub"])
                    call._finish(code, details, md)
                    _watchdog.call_finished(wd_tok, error=True)
                    from tpurpc.utils.timers import run_blocking

                    def replay():
                        if state["claimed"]:
                            return  # expired while queued
                        try:
                            start_attempt()
                        except BaseException as exc:
                            if claim():
                                timer = state.get("timer")
                                if timer is not None:
                                    timer.cancel()
                                if fut.set_running_or_notify_cancel():
                                    fut.set_exception(exc)

                    run_blocking(replay)
                    return
                if not claim():
                    return
                timer = state.get("timer")
                if timer is not None:
                    timer.cancel()
                call._finish(code, details, md)
                _watchdog.call_finished(wd_tok,
                                        error=code is not StatusCode.OK)
                if not fut.set_running_or_notify_cancel():
                    return  # caller cancelled the future; drop the result
                if code is not StatusCode.OK:
                    exc = RpcError(code, details, md)
                    if refused:
                        exc._tpurpc_refused = True
                    fut.set_exception(exc)
                elif len(msgs) != 1:
                    fut.set_exception(RpcError(
                        StatusCode.INTERNAL,
                        "unary call received no response" if not msgs
                        else "unary call received multiple responses"))
                else:
                    try:
                        fut.set_result(
                            _deserialize(self._mc._deser, msgs[0]))
                    except BaseException as exc:  # a raising deserializer
                        fut.set_exception(exc)    # fails, never hangs
                now = time.perf_counter_ns()
                _PIPE_CALL_US.record((now - t_start) // 1000)
                if st._t_terminal:
                    _PIPE_DEMUX_US.record((now - st._t_terminal) // 1000)

            # Hook AFTER the send: the terminal may already have been
            # delivered (fast server + slow caller), in which case st.done
            # is set and the hook will never fire — complete from here
            # instead. cur["fired"] makes the two funnels once-only.
            st.on_terminal = complete
            if st.done:
                complete()
            self._ensure_pump(conn)

        with self._lock:
            self._inflight += 1
        try:
            start_attempt()
        except BaseException:
            with self._lock:
                self._inflight -= 1
            self._window.release()
            raise
        if deadline is not None:
            # No thread waits on this call, so the deadline needs its own
            # watchdog: expire RSTs the CURRENT attempt's stream (endpoint
            # write — off the wheel thread) and fails the future. One
            # absolute deadline covers every replay.
            from tpurpc.utils.timers import run_blocking, schedule

            def expire():
                if not claim():
                    return
                cur = state["cur"]
                if cur is not None:
                    cur["call"]._expire()
                    _watchdog.call_finished(cur["wd"], error=True)
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(RpcError(
                        StatusCode.DEADLINE_EXCEEDED,
                        "deadline exceeded awaiting pipelined response"))

            state["timer"] = schedule(
                max(0.0, deadline - time.monotonic()),
                lambda: run_blocking(expire))
        return fut

    # -- pump-mode servicing --------------------------------------------------

    def _ensure_pump(self, conn: _Connection) -> None:
        """Pump-mode connections have no reader thread: with every caller
        detached (futures, nobody blocking in _pump_wait), the transport
        would never be drained. One servicing thread per live connection
        pumps while this pipeline has calls in flight."""
        if not conn._pump_mode:
            return
        key = id(conn)
        with self._lock:
            t = self._pump_threads.get(key)
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=self._pump_loop, args=(conn, key),
                                 daemon=True, name="tpurpc-pipeline-pump")
            self._pump_threads[key] = t
        t.start()

    def _pump_loop(self, conn: _Connection, key: int) -> None:
        try:
            while True:
                conn._pump_wait(
                    lambda: self._idle() or not conn.alive, None)
                with self._lock:
                    if self._idle() or not conn.alive:
                        self._pump_threads.pop(key, None)
                        return
        except Exception:
            with self._lock:
                self._pump_threads.pop(key, None)

    def _idle(self) -> bool:
        return self._inflight == 0 or self._closed

    def close(self) -> None:
        """Stop servicing. Outstanding futures still resolve off the
        reader thread; pump-mode servicing threads wind down."""
        with self._lock:
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _RetryingStreamCall:
    """Call-shaped wrapper retrying a server-streaming RPC that failed
    BEFORE its first response message (gRPC's retry rule for streams: once
    anything was delivered the call is committed). The request is unary,
    so replay is always possible. Start failures (dial, admission) consume
    retry attempts exactly like stream failures; one attempt/backoff
    budget spans the whole call. Cancellation during a backoff sleep stops
    further replays."""

    def __init__(self, mc: "UnaryStream", request, timeout, metadata,
                 policy: "RetryPolicy", wait_for_ready: bool = False,
                 throttle=None):
        self._inner: Optional[Call] = None  # first: __getattr__ recursion guard
        self._mc = mc
        self._request = request
        self._deadline = (None if timeout is None
                          else time.monotonic() + timeout)
        self._metadata = metadata
        self._policy = policy
        self._throttle = throttle  # channel-wide gRFC A6 token bucket
        self._wait_for_ready = wait_for_ready
        self._attempt = 0
        self._backoff = policy.initial_backoff
        self._cancelled = False
        self._start_with_retry()  # eager start, grpcio semantics

    def _handle_failure(self, exc: RpcError, committed: bool) -> None:
        """Count the attempt; sleep for the backoff; or re-raise."""
        self._attempt += 1
        retryable = _status_of(exc) in self._policy.retryable_codes
        if self._throttle is not None and retryable:
            self._throttle.record_failure()
        if (self._cancelled or committed
                or self._attempt >= self._policy.max_attempts
                or not retryable
                or (self._throttle is not None
                    and not self._throttle.allow_retry())):
            raise exc
        sleep = self._policy.next_sleep(self._backoff, self._deadline)
        pushback = _pushback_s(exc)  # admission shed: server-named floor
        if pushback is not None:
            sleep = pushback if sleep is None else max(sleep, pushback)
            if (self._deadline is not None
                    and time.monotonic() + sleep >= self._deadline):
                sleep = None
        if sleep is None:
            raise exc
        time.sleep(sleep)
        self._backoff *= self._policy.backoff_multiplier
        if self._cancelled:  # cancelled while we slept: stop replaying
            raise exc

    def _start_with_retry(self) -> None:
        while True:
            try:
                remaining = (None if self._deadline is None
                             else max(0.0, self._deadline - time.monotonic()))
                _, _, self._inner = self._mc._start(
                    self._metadata, remaining, first_request=self._request,
                    wait_for_ready=self._wait_for_ready)
                return
            except RpcError as exc:
                self._handle_failure(exc, committed=False)

    def messages(self) -> Iterator[object]:
        while True:
            delivered = False
            try:
                for msg in self._inner.messages():
                    delivered = True
                    yield msg
                if self._throttle is not None:
                    self._throttle.record_success()
                return
            except RpcError as exc:
                self._handle_failure(exc, committed=delivered)
                self._start_with_retry()

    def __iter__(self):
        return self.messages()

    def cancel(self):
        self._cancelled = True
        if self._inner is not None:
            self._inner.cancel()

    def __getattr__(self, name):
        # full Call-surface delegation (time_remaining, device_ring, ...)
        # to the CURRENT attempt's call
        return getattr(self._inner, name)


def _drain_single_response(messages) -> object:
    """The exactly-one-response rule, shared by both transports (identical
    status details either way)."""
    response = None
    got = False
    for msg in messages:
        if got:
            raise RpcError(StatusCode.INTERNAL,
                           "unary call received multiple responses")
        response, got = msg, True
    if not got:
        raise RpcError(StatusCode.INTERNAL, "unary response missing")
    return response


class UnaryStream(_MultiCallable):
    def __call__(self, request, timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None, **grpcio_kw):
        _reject_call_credentials(grpcio_kw)
        policy, timeout, throttle, wfr, _hedging = self._channel._call_plan(
            self._method, timeout, bool(grpcio_kw.get("wait_for_ready")))
        # Native fast path (same eligibility as the other shapes; retrying
        # and wait-for-ready calls stay on the Python transport —
        # _RetryingStreamCall's first-response rule and the queue-until-
        # ready dial loop are built on its Call internals)
        if (policy is None and self._allow_native and not metadata
                and not wfr
                # cheap eligibility FIRST (same gates _try_native_stream
                # re-checks): when the call is headed for the Python path
                # anyway, don't serialize here only to have _start
                # re-serialize the same request (ADVICE r4 #3)
                and not self._instruments_live()
                and self._channel._native_fast() is not None):
            # serialize EAGERLY: the Python path raises serializer errors
            # at call time (_start serializes first_request inline), and
            # the native path must not defer them to first iteration
            raw = self._ser(request)
            nsc = self._try_native_stream(iter([raw]), timeout,
                                          pre_serialized=True)
            if nsc is not None:
                return nsc
        if policy is None:
            conn, st, call = self._start(
                metadata, timeout, first_request=request,
                wait_for_ready=wfr)
            return call
        return _RetryingStreamCall(self, request, timeout, metadata, policy,
                                   wfr, throttle=throttle)


class StreamUnary(_MultiCallable):
    def __call__(self, request_iterator: Iterable,
                 timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None, **grpcio_kw):
        _reject_call_credentials(grpcio_kw)
        _, timeout, _, wfr, _hedging = self._channel._call_plan(
            self._method, timeout, bool(grpcio_kw.get("wait_for_ready")))
        if self._allow_native and not metadata and not wfr:
            nsc = self._try_native_stream(request_iterator, timeout)
            if nsc is not None:
                return _drain_single_response(nsc)
        conn, st, call = self._start(
            metadata, timeout, wait_for_ready=wfr)
        sender = threading.Thread(
            target=self._send_stream, args=(conn, st, request_iterator, call),
            daemon=True)
        sender.start()
        response = _drain_single_response(call.messages())
        sender.join(timeout=5)
        return response


class _NativeStreamCall:
    """Call-shaped bidi stream over a native ``NativeCall``. The RPC starts
    EAGERLY (the Python transport's semantics: requests flow before the
    first response is consumed), cancel() is cross-thread-safe (a plain C
    call, unlike closing a running generator), responses honor the
    channel's receive limit, and completions feed the channel's call
    counters — the parity points the native unary path already carries."""

    def __init__(self, channel: "Channel", nc, serializer, deserializer,
                 request_iterator, timeout: Optional[float]):
        self._nc = nc
        self._deser = deserializer
        self._code: Optional[StatusCode] = None
        self._details = ""
        self._deadline = (None if timeout is None
                          else time.monotonic() + timeout)
        self._recv_limit = channel.max_receive_message_length
        self._counters = channel.call_counters
        self._counters.on_start()
        self._finished = False
        self._finish_lock = make_lock("_NativeStreamCall._finish_lock")
        self._callbacks: list = []
        self._app_exc: list = []
        self._sender = threading.Thread(
            target=self._pump_requests, args=(request_iterator, serializer),
            daemon=True)
        self._sender.start()

    def _pump_requests(self, request_iterator, serializer) -> None:
        try:
            for item in request_iterator:
                self._nc.write(serializer(item))
            self._nc.writes_done()
        except RpcError:
            pass  # the read side surfaces the status
        except BaseException as exc:  # the app's iterator/serializer raised
            self._app_exc.append(exc)
            self._nc.cancel()  # both sides unblock; reader sees CANCELLED

    def _finish(self) -> None:
        with self._finish_lock:
            if self._finished:
                return
            self._finished = True
        if self._sender.is_alive():
            # early consumer exit with requests still flowing: RST first
            # so the blocked writer fails fast, THEN join (destroying the
            # call under a live writer is a native use-after-free)
            self._nc.cancel()
        self._sender.join()
        code, details = self._nc.finish()
        self._code, self._details = code, details
        self._nc.close()
        self._counters.on_finish(code is StatusCode.OK)
        for cb in self._callbacks:
            try:
                cb()
            except Exception:
                pass

    def __iter__(self):
        return self

    def __next__(self):
        msg = self._nc.read()
        if msg is None:
            self._finish()
            if self._app_exc:
                raise self._app_exc[0]
            if self._code is not StatusCode.OK:
                raise RpcError(self._code, self._details)
            raise StopIteration
        if self._recv_limit is not None and len(msg) > self._recv_limit:
            self._nc.cancel()
            self._finish()
            self._code = StatusCode.RESOURCE_EXHAUSTED
            self._details = ("received message larger than "
                            "max_receive_message_length")
            raise RpcError(self._code, self._details)
        return _deserialize(self._deser, msg)

    def __del__(self):
        # abandoned stream: RST + teardown so the server stops producing
        try:
            if not self._finished:
                self._nc.cancel()
                self._finish()
        except Exception:
            pass

    # -- grpc Call surface ---------------------------------------------------

    def cancel(self) -> None:
        self._nc.cancel()  # thread-safe: plain C call, reader unblocks

    def code(self) -> Optional[StatusCode]:
        return self._code

    def details(self) -> str:
        return self._details

    def is_active(self) -> bool:
        return not self._finished

    def time_remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def add_callback(self, callback) -> bool:
        with self._finish_lock:
            if not self._finished:
                self._callbacks.append(callback)
                return True
        return False

    def initial_metadata(self):
        return []

    def trailing_metadata(self):
        return []

    def messages(self) -> Iterator[object]:
        """Call-surface parity: response iteration (UnaryStream callers
        use this name; on this wrapper it IS the iterator)."""
        return self

    def device_ring(self):
        """Call-surface parity: the native loop has no device-ring seam
        (the TPU platform is never fast-path eligible), so callers get
        the documented off-platform answer and fall back to host decode."""
        return None


class StreamStream(_MultiCallable):
    def __call__(self, request_iterator: Iterable,
                 timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None, **grpcio_kw):
        _reject_call_credentials(grpcio_kw)
        _, timeout, _, wfr, _hedging = self._channel._call_plan(
            self._method, timeout, bool(grpcio_kw.get("wait_for_ready")))
        # Native bidi fast path, same eligibility story as UnaryUnary:
        # plain calls on eligible channels stream through libtpurpc's
        # loop (the duplex/tensor hot path). Callers needing per-call
        # metadata (or queue-until-ready) stay on the Python transport.
        if self._allow_native and not metadata and not wfr:
            nsc = self._try_native_stream(request_iterator, timeout)
            if nsc is not None:
                return nsc
        conn, st, call = self._start(
            metadata, timeout, wait_for_ready=wfr)
        sender = threading.Thread(
            target=self._send_stream, args=(conn, st, request_iterator, call),
            daemon=True)
        sender.start()
        return call


def channel_ready_future(channel: "Channel"):
    """grpc.channel_ready_future analog: a Future resolving (with None)
    once the channel reports READY; get_state(try_to_connect=True) drives
    the dial. Cancel the future to stop waiting early — an abandoned,
    uncancelled future keeps watching only while the channel object stays
    alive (the watcher holds a weakref, so it can't pin the Channel from
    GC or outlive a dropped one)."""
    import concurrent.futures
    import weakref

    fut: "concurrent.futures.Future" = concurrent.futures.Future()
    chref = weakref.ref(channel)

    def watch():
        while not fut.cancelled():
            ch = chref()
            if ch is None:
                return  # channel was dropped; nobody can ever see READY
            state = ch.get_state(try_to_connect=True)
            del ch  # don't pin the channel across the sleep
            if state is ChannelConnectivity.READY:
                if fut.set_running_or_notify_cancel():
                    fut.set_result(None)
                return
            if state is ChannelConnectivity.SHUTDOWN:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(
                        RpcError(StatusCode.UNAVAILABLE, "channel closed"))
                return
            time.sleep(0.02)

    threading.Thread(target=watch, daemon=True,
                     name="tpurpc-channel-ready").start()
    return fut


def insecure_channel(target: str, **kwargs) -> Channel:
    """grpcio-shaped constructor."""
    return Channel(target, **kwargs)


def secure_channel(target: str, credentials, **kwargs) -> Channel:
    """grpcio-shaped constructor: pass the result of
    :func:`tpurpc.rpc.credentials.ssl_channel_credentials`."""
    return Channel(target, credentials=credentials, **kwargs)
