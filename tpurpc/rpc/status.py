"""RPC status codes and errors — the surface contract of every call.

The 16 canonical codes mirror gRPC's ``grpc_status_code`` (reference:
``include/grpc/impl/codegen/status.h``); the transport→status mapping rule comes from
the fork's endpoint error annotation: transport failures surface as ``UNAVAILABLE`` so
the client channel knows it may reconnect and retry (``rdma_bp_posix.cc:86-96``).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional, Sequence, Tuple

Metadata = Sequence[Tuple[str, "str | bytes"]]
Serializer = Callable[[object], bytes]
Deserializer = Callable[[bytes], object]


def identity_codec(x):
    """Default (de)serializer: the application speaks raw bytes."""
    return x


def deserialize(deserializer, buf):
    """Apply ``deserializer`` to a received message buffer.

    Assembled messages arrive as memoryviews over detached Assembly storage
    (``tpurpc.rpc.frame``). grpcio's contract hands deserializers *bytes*, so
    views are materialized first (a real, LEDGERED host copy) — except for
    deserializers marked ``alias_ok = True`` (the tensor codec), which decode
    zero-copy straight over the view. Only the raw-bytes surface pays the
    materialization; the bulk tensor path keeps the saved pass."""
    if isinstance(buf, memoryview) and not getattr(deserializer, "alias_ok",
                                                   False):
        from tpurpc.tpu import ledger as _ledger

        _ledger.host_copy(len(buf))
        buf = bytes(buf)
    return deserializer(buf)


class StatusCode(enum.IntEnum):
    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    ALREADY_EXISTS = 6
    PERMISSION_DENIED = 7
    RESOURCE_EXHAUSTED = 8
    FAILED_PRECONDITION = 9
    ABORTED = 10
    OUT_OF_RANGE = 11
    UNIMPLEMENTED = 12
    INTERNAL = 13
    UNAVAILABLE = 14
    DATA_LOSS = 15
    UNAUTHENTICATED = 16


class ChannelConnectivity(enum.Enum):
    """grpc.ChannelConnectivity analog (connectivity_state.h states).

    Surfaced by :meth:`tpurpc.rpc.channel.Channel.get_state`; the mapping
    from subchannel reality is documented there."""

    IDLE = 0
    CONNECTING = 1
    READY = 2
    TRANSIENT_FAILURE = 3
    SHUTDOWN = 4


class RpcError(Exception):
    """Raised on the client when a call terminates with a non-OK status."""

    def __init__(self, code: StatusCode, details: str = "",
                 trailing_metadata: Optional[Metadata] = None):
        super().__init__(f"{code.name}: {details}")
        self._code = code
        self._details = details
        self._trailing = tuple(trailing_metadata or ())

    def code(self) -> StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def trailing_metadata(self) -> Metadata:
        return self._trailing


class AbortError(Exception):
    """Raised inside a server handler by ``context.abort`` to terminate the RPC."""

    def __init__(self, code: StatusCode, details: str):
        super().__init__(f"{code.name}: {details}")
        self.code = code
        self.details = details
