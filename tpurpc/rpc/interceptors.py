"""Interceptors + fault injection.

grpcio-shaped interception so middleware ports directly:

* server: objects with ``intercept_service(continuation, details)`` →
  ``Server(interceptors=[...])`` (grpcio ``grpc.ServerInterceptor``)
* client: :func:`intercept_channel` wrapping the four multicallable shapes
  (grpcio ``grpc.intercept_channel``)

On top of them, :class:`FaultInjector` reproduces the reference's
fault_injection filter (``ext/filters/fault_injection/
fault_injection_filter.cc`` — SURVEY.md §5 failure-injection row):
per-method abort code/probability and injected delay, configured
programmatically instead of via service config JSON.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, Optional, Sequence

from tpurpc.rpc.status import AbortError, Metadata, StatusCode


class HandlerCallDetails:
    __slots__ = ("method", "invocation_metadata")

    def __init__(self, method: str, invocation_metadata: Metadata):
        self.method = method
        self.invocation_metadata = invocation_metadata


class ServerInterceptor:
    """Override intercept_service; return a handler (possibly wrapped)."""

    def intercept_service(self, continuation: Callable, details: HandlerCallDetails):
        return continuation(details)


def apply_server_interceptors(handler, method: str, metadata: Metadata,
                              interceptors: Sequence[ServerInterceptor]):
    """Run the chain innermost-last, like grpcio."""
    details = HandlerCallDetails(method, metadata)

    def base(_details):
        return handler

    continuation = base
    for icpt in reversed(list(interceptors)):
        continuation = (lambda d, icpt=icpt, nxt=continuation:
                        icpt.intercept_service(nxt, d))
    return continuation(details)


# -- client side -------------------------------------------------------------

class ClientCallDetails:
    __slots__ = ("method", "timeout", "metadata")

    def __init__(self, method: str, timeout: Optional[float],
                 metadata: Optional[Metadata]):
        self.method = method
        self.timeout = timeout
        self.metadata = metadata


class ClientInterceptor:
    """One hook for all four shapes (grpcio splits these into four ABCs;
    the merged form is what nearly every real interceptor writes anyway)."""

    def intercept_call(self, continuation: Callable,
                       details: ClientCallDetails, request_or_iterator):
        return continuation(details, request_or_iterator)


class _InterceptedMultiCallable:
    def __init__(self, inner, method: str,
                 interceptors: Sequence[ClientInterceptor]):
        self._inner = inner
        self._method = method
        self._interceptors = list(interceptors)

    def _invoke(self, request_or_iterator, timeout, metadata, with_call: bool):
        def base(details: ClientCallDetails, req):
            if with_call:
                return self._inner.with_call(req, timeout=details.timeout,
                                             metadata=details.metadata)
            return self._inner(req, timeout=details.timeout,
                               metadata=details.metadata)

        continuation = base
        for icpt in reversed(self._interceptors):
            continuation = (lambda d, r, icpt=icpt, nxt=continuation:
                            icpt.intercept_call(nxt, d, r))
        return continuation(ClientCallDetails(self._method, timeout, metadata),
                            request_or_iterator)

    def __call__(self, request_or_iterator, timeout=None, metadata=None):
        return self._invoke(request_or_iterator, timeout, metadata, False)

    def with_call(self, request_or_iterator, timeout=None, metadata=None):
        return self._invoke(request_or_iterator, timeout, metadata, True)


class _InterceptedChannel:
    def __init__(self, channel, interceptors: Sequence[ClientInterceptor]):
        self._channel = channel
        self._interceptors = list(interceptors)

    def _wrap(self, factory, method, *codecs):
        return _InterceptedMultiCallable(factory(method, *codecs), method,
                                         self._interceptors)

    def unary_unary(self, method, *codecs):
        return self._wrap(self._channel.unary_unary, method, *codecs)

    def unary_stream(self, method, *codecs):
        return self._wrap(self._channel.unary_stream, method, *codecs)

    def stream_unary(self, method, *codecs):
        return self._wrap(self._channel.stream_unary, method, *codecs)

    def stream_stream(self, method, *codecs):
        return self._wrap(self._channel.stream_stream, method, *codecs)

    def ping(self, timeout: float = 5.0):
        return self._channel.ping(timeout)

    def close(self):
        return self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def intercept_channel(channel, *interceptors: ClientInterceptor):
    return _InterceptedChannel(channel, interceptors)


# -- fault injection ---------------------------------------------------------

class FaultConfig:
    __slots__ = ("abort_code", "abort_message", "abort_fraction",
                 "delay_s", "delay_fraction")

    def __init__(self, abort_code: Optional[StatusCode] = None,
                 abort_message: str = "injected failure",
                 abort_fraction: float = 0.0, delay_s: float = 0.0,
                 delay_fraction: float = 0.0):
        self.abort_code = abort_code
        self.abort_message = abort_message
        self.abort_fraction = abort_fraction
        self.delay_s = delay_s
        self.delay_fraction = delay_fraction


class FaultInjector(ServerInterceptor):
    """Per-method delay/abort injection (fault_injection_filter.cc parity).

    ``configs`` maps method path (or ``"*"``) → :class:`FaultConfig`.
    Deterministic under a seeded ``rng`` for tests.
    """

    def __init__(self, configs: Dict[str, FaultConfig],
                 rng: Optional[random.Random] = None):
        self.configs = dict(configs)
        self._rng = rng or random.Random()

    def intercept_service(self, continuation, details: HandlerCallDetails):
        cfg = self.configs.get(details.method) or self.configs.get("*")
        handler = continuation(details)
        if cfg is None or handler is None:
            return handler

        from tpurpc.rpc.server import RpcMethodHandler

        inner = handler.behavior

        def faulty(request_or_iterator, context):
            if cfg.delay_s and self._rng.random() < cfg.delay_fraction:
                time.sleep(cfg.delay_s)
            if (cfg.abort_code is not None
                    and self._rng.random() < cfg.abort_fraction):
                raise AbortError(cfg.abort_code, cfg.abort_message)
            return inner(request_or_iterator, context)

        return RpcMethodHandler(handler.kind, faulty,
                                handler.request_deserializer,
                                handler.response_serializer)
