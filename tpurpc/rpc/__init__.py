"""tpurpc.rpc — call/stream layer over the endpoint seam (SURVEY.md §7 stage 3).

grpcio-shaped public surface so application code ports mechanically:

    channel = tpurpc.rpc.insecure_channel("host:port")
    hello = channel.unary_unary("/demo.Greeter/SayHello")
    reply = hello(b"world", timeout=5)

    srv = tpurpc.rpc.server()
    srv.add_service("demo.Greeter", {"SayHello": tpurpc.rpc.unary_unary_rpc_method_handler(fn)})
    srv.add_insecure_port("0.0.0.0:50051"); srv.start()
"""

from tpurpc.rpc.status import (AbortError, ChannelConnectivity, Metadata,
                               RpcError, StatusCode)
from tpurpc.rpc.channel import (Channel, channel_ready_future,
                                insecure_channel)
from tpurpc.rpc.server import (
    Server,
    ServerContext,
    RpcMethodHandler,
    inproc_channel,
    method_handlers_generic_handler,
    server,
    stream_stream_rpc_method_handler,
    stream_unary_rpc_method_handler,
    unary_stream_rpc_method_handler,
    unary_unary_rpc_method_handler,
)

__all__ = [
    "AbortError", "Metadata", "RpcError", "StatusCode",
    "Channel", "channel_ready_future", "insecure_channel",
    "Server", "ServerContext", "RpcMethodHandler", "server", "inproc_channel",
    "method_handlers_generic_handler",
    "unary_unary_rpc_method_handler", "unary_stream_rpc_method_handler",
    "stream_unary_rpc_method_handler", "stream_stream_rpc_method_handler",
]

from tpurpc.rpc.interceptors import (ClientInterceptor, FaultConfig,
                                     FaultInjector, ServerInterceptor,
                                     intercept_channel)

__all__ += ["ClientInterceptor", "FaultConfig", "FaultInjector",
            "ServerInterceptor", "intercept_channel"]

from tpurpc.rpc.resolver import register_resolver, ring_hash_key

__all__ += ["register_resolver", "ring_hash_key"]

from tpurpc.rpc.channel import RetryPolicy

__all__ += ["RetryPolicy"]

# H2Channel is exported LAZILY: tpurpc.wire.h2_client imports
# tpurpc.wire.grpc_h2, which imports tpurpc.rpc.status — an eager import here
# makes any `import tpurpc.wire.grpc_h2`-first program hit this package's
# __init__ mid-cycle and crash on the partially initialized module.
__all__ += ["H2Channel"]


# "aio" stays OUT of __all__: star imports must not pay the asyncio
# import on the sync path (grpcio likewise keeps aio out of `import *`).
__all__ += ["ChannelConnectivity"]


def __getattr__(name):
    if name == "H2Channel":
        from tpurpc.wire.h2_client import H2Channel

        return H2Channel
    if name == "NativeChannel":
        from tpurpc.rpc.native_client import NativeChannel

        return NativeChannel
    if name == "aio":
        # lazy like grpc.aio: `import tpurpc.rpc as grpc; grpc.aio...`
        # works without paying the asyncio import on the sync path
        import tpurpc.rpc.aio as aio

        return aio
    raise AttributeError(f"module 'tpurpc.rpc' has no attribute {name!r}")

from tpurpc.rpc.channel import secure_channel  # noqa: E402
from tpurpc.rpc.credentials import (ChannelCredentials,  # noqa: E402
                                    ServerCredentials,
                                    insecure_for_testing_channel_credentials,
                                    ssl_channel_credentials,
                                    ssl_server_credentials)

__all__ += ["secure_channel", "ChannelCredentials", "ServerCredentials",
            "ssl_channel_credentials", "ssl_server_credentials",
            "insecure_for_testing_channel_credentials"]

from tpurpc.rpc.reflection import enable_server_reflection  # noqa: E402

__all__ += ["enable_server_reflection"]

from tpurpc.rpc.lookaside import (LoadBalancerServicer,  # noqa: E402
                                  enable_lookaside)

__all__ += ["LoadBalancerServicer", "enable_lookaside"]

from tpurpc.rpc.health import add_health_servicer  # noqa: E402

__all__ += ["add_health_servicer"]

from tpurpc.rpc.channelz_v1 import enable_channelz  # noqa: E402

__all__ += ["enable_channelz"]

__all__ += ["NativeChannel"]
