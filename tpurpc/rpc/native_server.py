"""Native server data plane for the Python :class:`tpurpc.rpc.Server`.

The reference's defining architecture is that EVERY language binding rides
the C core: a Python grpcio server is the C-core server with Python
handlers called back through the binding (``src/python/grpcio/grpc/
_server.py`` over ``_cygrpc``; SURVEY.md §2.4). This module is that seam
for tpurpc: eligible ring-platform connections accepted by the Python
server are handed — raw fd — to libtpurpc's shared-poller server
(``tpr_server_adopt_fd``, native/src/tpurpc_server.cc), which runs the
framing, ring pumping, and per-stream demux in C and calls back into the
registered Python handlers via ctypes trampolines. The Python data plane
(rpc/server.py) keeps serving everything else: TCP and h2 wire-compat
connections, TLS, servers with interceptors or connection-management knobs.

Measured effect (bench/results/scalability_1core.log): the native loop
serves 64B ring echo at ~116K RPC/s vs ~4.6K for the pure-Python path on
the same host — this seam is what closes VERDICT r3's "Python data plane
loses to TCP" gap, because the sweep's server is a plain Python Server.

Handler mapping:

- ``inline=True`` unary handlers → the native callback API (runs on the
  poller thread — the handler's existing MUST-NOT-BLOCK contract).
- Everything else → the native handler API: a native thread per call runs
  the Python behavior, which may block (thread-per-call is exactly the
  Python server's worker-pool semantics, minus the pool bound — gRPC's
  C-core sync server makes the same trade).

Context surface: :class:`NativeServerContext` implements the
grpcio-compatible subset the adopted path can honor (invocation metadata,
deadline, initial/trailing metadata, abort/set_code/set_details,
is_active). TLS-derived surfaces (auth_context, peer certs) never appear
here — adoption is gated to plaintext listeners.
"""

from __future__ import annotations

import ctypes
import os
import socket
import threading
import weakref
from typing import Optional

from tpurpc.obs import tracing as _tracing
from tpurpc.rpc.native_client import _u8_zc
from tpurpc.rpc.status import AbortError, StatusCode, deserialize
from tpurpc.utils.trace import TraceFlag

trace_nsrv = TraceFlag("native_server")

_MSG_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                           ctypes.c_void_p)
_HANDLER_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_void_p)

_bound = False
_bind_lock = threading.Lock()


def _lib():
    """The shared libtpurpc CDLL with the server symbols' signatures bound
    (the client loader owns the handle; signatures are set once)."""
    from tpurpc.rpc.native_client import _load

    lib = _load()
    global _bound
    with _bind_lock:
        if _bound:
            return lib
        lib.tpr_server_create.restype = ctypes.c_void_p
        lib.tpr_server_create.argtypes = [ctypes.c_int]
        lib.tpr_server_port.argtypes = [ctypes.c_void_p]
        lib.tpr_server_port.restype = ctypes.c_int
        lib.tpr_server_register.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            _HANDLER_FN, ctypes.c_void_p]
        lib.tpr_server_register_callback.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, _MSG_CB, ctypes.c_void_p]
        lib.tpr_server_register_default.argtypes = [ctypes.c_void_p,
                                                   _HANDLER_FN,
                                                   ctypes.c_void_p]
        lib.tpr_server_start.argtypes = [ctypes.c_void_p]
        lib.tpr_server_start.restype = ctypes.c_int
        lib.tpr_server_adopt_fd.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.POINTER(ctypes.c_uint8),
                                            ctypes.c_size_t]
        lib.tpr_server_adopt_fd.restype = ctypes.c_int
        lib.tpr_server_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_srv_recv.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.tpr_srv_recv.restype = ctypes.c_int
        lib.tpr_srv_send.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_size_t]
        lib.tpr_srv_send.restype = ctypes.c_int
        lib.tpr_srv_method.argtypes = [ctypes.c_void_p]
        lib.tpr_srv_method.restype = ctypes.c_char_p
        lib.tpr_srv_deadline_us.argtypes = [ctypes.c_void_p]
        lib.tpr_srv_deadline_us.restype = ctypes.c_int64
        lib.tpr_srv_set_details.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpr_srv_metadata_count.argtypes = [ctypes.c_void_p]
        lib.tpr_srv_metadata_count.restype = ctypes.c_size_t
        lib.tpr_srv_metadata_get.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p)]
        lib.tpr_srv_metadata_get.restype = ctypes.c_int
        lib.tpr_srv_send_initial_md.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p,
                                                ctypes.c_char_p]
        lib.tpr_srv_add_trailing_md.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p,
                                                ctypes.c_char_p]
        lib.tpr_srv_cancelled.argtypes = [ctypes.c_void_p]
        lib.tpr_srv_cancelled.restype = ctypes.c_int
        lib.tpr_srv_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _bound = True
    return lib


_INT64_MAX = 2**63 - 1


class NativeServerContext:
    """grpcio-compatible context over a native ``tpr_server_call``."""

    def __init__(self, lib, call):
        self._lib = lib
        self._call = call
        self._trailing = ()
        self._code: Optional[StatusCode] = None
        self._details = ""
        self._initial_sent = False

    def invocation_metadata(self):
        lib, call = self._lib, self._call
        out = []
        key = ctypes.c_char_p()
        val = ctypes.c_char_p()
        for i in range(lib.tpr_srv_metadata_count(call)):
            if lib.tpr_srv_metadata_get(call, i, ctypes.byref(key),
                                        ctypes.byref(val)) == 0:
                out.append((key.value.decode("utf-8", "replace"),
                            val.value.decode("utf-8", "replace")))
        return out

    def peer(self) -> str:
        return "ring:native"  # adopted conns are local ring transports

    def auth_context(self) -> dict:
        return {}  # adoption is plaintext-only by eligibility

    def deadline_remaining(self) -> Optional[float]:
        us = self._lib.tpr_srv_deadline_us(self._call)
        if us >= _INT64_MAX:
            return None
        return us / 1e6

    time_remaining = deadline_remaining

    def is_active(self) -> bool:
        return not self._lib.tpr_srv_cancelled(self._call)

    def cancel(self) -> None:
        pass  # server-side local cancel: the native loop reaps at finish

    def set_trailing_metadata(self, metadata) -> None:
        self._trailing = metadata
        for k, v in metadata:
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            self._lib.tpr_srv_add_trailing_md(self._call, str(k).encode(),
                                              str(v).encode())

    def set_code(self, code: StatusCode) -> None:
        self._code = code

    def set_details(self, details: str) -> None:
        self._details = details
        self._lib.tpr_srv_set_details(self._call, details.encode())

    def abort(self, code: StatusCode, details: str = ""):
        if code is StatusCode.OK:
            raise ValueError("abort with OK is invalid")
        raise AbortError(code, details)

    def send_initial_metadata(self, metadata) -> None:
        if self._initial_sent:
            raise RuntimeError("initial metadata already sent")
        self._initial_sent = True
        for k, v in metadata:
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            self._lib.tpr_srv_send_initial_md(self._call, str(k).encode(),
                                              str(v).encode())

    # internal ---------------------------------------------------------------

    def _finish_code(self, default_ok: bool = True) -> int:
        if self._code is not None:
            return int(self._code.value)
        return 0 if default_ok else 13


def _take(lib, pptr, plen) -> memoryview:
    """Adopt the C plane's message buffer ZERO-COPY.

    Returns a writable memoryview directly over the malloc'd buffer
    ``tpr_srv_recv`` handed us; a finalizer frees it when the last Python
    reference (the view, or numpy arrays decoded over it) dies. The old
    ``ctypes.string_at`` here was one whole extra pass over every received
    message — and its read-only ``bytes`` result forced ``to_jax`` off the
    writable-buffer dlpack import on top of that. ``alias_ok``
    deserializers (the tensor codec) decode straight over this view;
    everyone else gets grpcio-contract ``bytes`` via ``deserialize``.
    """
    n = plen.value
    if not n:
        if pptr:
            lib.tpr_srv_buf_free(pptr)
        return memoryview(b"")
    addr = ctypes.cast(pptr, ctypes.c_void_p).value
    raw = (ctypes.c_uint8 * n).from_address(addr)
    # a fresh pointer object: the caller's pptr is reused per recv loop
    owned = ctypes.cast(ctypes.c_void_p(addr),
                        ctypes.POINTER(ctypes.c_uint8))
    weakref.finalize(raw, lib.tpr_srv_buf_free, owned)
    return memoryview(raw).cast("B")


class NativeDataplane:
    """One ``tpr_server`` carrying adopted connections for a Python Server."""

    def __init__(self, py_server):
        self._lib = _lib()
        self._py_server = py_server
        # The native server's own listener is an implementation detail (it
        # binds an ephemeral loopback port nobody is told about); adopted
        # fds are the only traffic source.
        self._srv = self._lib.tpr_server_create(0)
        if not self._srv:
            raise OSError("tpr_server_create failed")
        self._refs = []  # CFUNCTYPE objects must outlive the server
        # inline unary handlers get the poller-thread reactor path; every
        # OTHER call resolves DYNAMICALLY through the default trampoline —
        # which covers grpcio generic handlers and late registrations the
        # same way the Python plane's per-call _lookup does
        for path, handler in dict(py_server._methods).items():
            if handler.kind == "unary_unary" and handler.inline:
                self._register_inline(path, handler)
        self._register_default()
        if self._lib.tpr_server_start(self._srv) != 0:
            self._lib.tpr_server_destroy(self._srv)
            raise OSError("tpr_server_start failed")
        self._closed = False
        self._lock = threading.Lock()

    # -- handler trampolines -------------------------------------------------

    def _register_inline(self, path: str, handler) -> None:
        # poller-thread reactor path (the handler's existing
        # must-not-block contract, RpcMethodHandler.inline)
        lib = self._lib

        def msg_cb(call, data, length, _ud, _h=handler):
            try:
                body = ctypes.string_at(data, length) if length else b""
                ctx = NativeServerContext(lib, call)
                try:
                    resp = _h.behavior(_h.request_deserializer(body), ctx)
                except AbortError as exc:
                    lib.tpr_srv_set_details(call, exc.details.encode())
                    return int(exc.code.value)
                raw = _h.response_serializer(resp)
                # zero-copy for bytes (tpr_srv_send consumes the buffer
                # before returning: rdv memcpy or framed ring write inline)
                buf, blen = _u8_zc(raw)
                lib.tpr_srv_send(call, buf, blen)
                return ctx._finish_code()  # 0 unless set_code()
            except Exception as exc:  # handler raised: INTERNAL
                try:
                    lib.tpr_srv_set_details(call, repr(exc).encode())
                except Exception:
                    pass
                return 13

        cb = _MSG_CB(msg_cb)
        self._refs.append(cb)
        lib.tpr_server_register_callback(self._srv, path.encode(), cb, None)

    def _register_default(self) -> None:
        lib = self._lib

        def handler_fn(call, _ud):
            try:
                ctx = NativeServerContext(lib, call)
                path = lib.tpr_srv_method(call).decode("utf-8", "replace")
                # the Python plane's dynamic resolution (exact methods,
                # grpcio generic handlers, late registrations)
                _h = self._py_server._lookup(path, ctx.invocation_metadata())
                if _h is None:
                    lib.tpr_srv_set_details(
                        call, f"unknown method {path}".encode())
                    return 12  # UNIMPLEMENTED

                def requests():
                    pptr = ctypes.POINTER(ctypes.c_uint8)()
                    plen = ctypes.c_size_t()
                    while True:
                        r = lib.tpr_srv_recv(call, ctypes.byref(pptr),
                                             ctypes.byref(plen))
                        if r != 1:
                            return
                        yield deserialize(_h.request_deserializer,
                                          _take(lib, pptr, plen))

                def send(resp) -> int:
                    raw = _h.response_serializer(resp)
                    # zero-copy for bytes: tpr_srv_send consumes the
                    # buffer (rdv memcpy or framed write) before returning
                    buf, blen = _u8_zc(raw)
                    return lib.tpr_srv_send(call, buf, blen)

                # tpurpc-scope (ISSUE 4): the trace context a sampled
                # caller shipped through tpr_call_start's metadata — same
                # wire key as the Python plane, installed as this handler
                # thread's ambient so handler spans (and the batcher's
                # batch-wait/infer) attribute to the caller's trace_id.
                tctx = None
                if _tracing.LIVE:
                    for _k, _v in ctx.invocation_metadata():
                        if _k == _tracing.HEADER:
                            # adopt (not bare decode): a tail-provisional
                            # caller opens this process's pending buffer so
                            # handler spans join the same tail decision
                            tctx = _tracing.adopt(_v)
                            break
                # tpurpc-blackbox: the native plane registers with the
                # stall watchdog and makes the tail-capture decision like
                # the Python plane (ISSUE 5 — both planes)
                import time as _time

                from tpurpc.obs import watchdog as _watchdog

                wd_tok = _watchdog.call_started(
                    path, tctx.trace_id if tctx is not None else 0)
                t0 = _time.monotonic_ns()
                rc = 13
                try:
                    try:
                        with _tracing.use(tctx) if tctx is not None \
                                else _tracing.NULL_CM:
                            if _h.kind == "unary_unary":
                                req = next(requests(), None)
                                if req is None:
                                    return 13  # half-close with no message
                                with _tracing.span("handler", tctx):
                                    resp = _h.behavior(req, ctx)
                                if send(resp) != 0:
                                    return 14  # UNAVAILABLE: conn died
                            elif _h.kind == "unary_stream":
                                req = next(requests(), None)
                                if req is None:
                                    return 13
                                for resp in _h.behavior(req, ctx):
                                    if send(resp) != 0:
                                        return 14
                            elif _h.kind == "stream_unary":
                                if send(_h.behavior(requests(), ctx)) != 0:
                                    return 14
                            else:  # stream_stream
                                for resp in _h.behavior(requests(), ctx):
                                    if send(resp) != 0:
                                        return 14
                    except AbortError as exc:
                        lib.tpr_srv_set_details(call, exc.details.encode())
                        rc = int(exc.code.value)
                        return rc
                    rc = ctx._finish_code()
                    return rc
                finally:
                    _watchdog.call_finished(wd_tok, error=rc != 0)
                    _tracing.tail_decide(tctx, _time.monotonic_ns() - t0,
                                         error=rc != 0, method=path)
            except Exception as exc:  # handler raised: INTERNAL
                try:
                    lib.tpr_srv_set_details(call, repr(exc).encode())
                except Exception:
                    pass
                return 13

        fn = _HANDLER_FN(handler_fn)
        self._refs.append(fn)
        lib.tpr_server_register_default(self._srv, fn, None)

    # -- adoption ------------------------------------------------------------

    def adopt(self, sock: socket.socket) -> bool:
        """Take ownership of an accepted socket; True means the caller
        must forget it. The _closed check happens under the same lock
        close() takes, so tpr_server_adopt_fd cannot race destroy; its
        defensive failure branch still CONSUMES the socket (detach already
        ran — handing a dead fd back for the Python path to serve would
        be worse than dropping one connection; the client re-dials)."""
        with self._lock:
            if self._closed:
                return False  # socket untouched: Python path serves it
            fd = sock.detach()
            if self._lib.tpr_server_adopt_fd(self._srv, fd, None, 0) != 0:
                os.close(fd)
                return True  # consumed-and-dropped; never serve a dead fd
            trace_nsrv.log("adopted fd %d onto the native data plane", fd)
            return True

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # NOTE: destroy blocks until handler threads drain; Python handlers
        # blocked in tpr_srv_recv are woken by the per-conn teardown.
        self._lib.tpr_server_destroy(self._srv)


def adoption_eligible(py_server) -> bool:
    """Whether THIS server's accepted ring connections may ride the native
    data plane. Conservative: every feature the native loop cannot honor
    keeps the whole server on the Python plane."""
    mode = os.environ.get("TPURPC_NATIVE_SERVER", "auto").lower()
    if mode in ("0", "off", "false"):
        return False
    if getattr(py_server, "_native_dataplane_opt", None) is False:
        return False  # Server(native_dataplane=False): bulk-optimized
    from tpurpc.utils.config import get_config

    cfg = get_config()
    if not (cfg.platform.is_ring and cfg.platform.name != "TPU"
            and cfg.ring_domain == "shm"):
        return False  # the native loop speaks shm rings (+ its own TCP)
    if py_server.interceptors:
        return False  # interceptor wrapping happens in the Python plane
    # (generic handlers are FINE: the default trampoline resolves methods
    # through the server's own _lookup per call, grpcio-style)
    if cfg.max_connection_age_ms > 0 or cfg.keepalive_time_ms > 0 \
            or cfg.client_idle_timeout_ms > 0:
        return False  # connection management lives in the Python plane
    try:
        return _lib() is not None
    except Exception:
        return False
