"""JSON service config: per-method timeout/retry delivered by the resolver.

The reference's client_channel consumes a service config attached to every
resolver result — per-method timeouts and retry policies arrive from the
name resolver, not from call sites (``ext/filters/client_channel/
service_config.cc``, ``retry_service_config.cc``), with retry THROTTLING
shared channel-wide (``retry_throttle.cc``, gRFC A6). tpurpc mirrors the
shape:

* resolvers may return ``(addresses, service_config_dict)`` — see
  :func:`tpurpc.rpc.resolver.resolve_target_full`; the channel parses the
  dict through :class:`ServiceConfig` and consults it per method.
* the JSON schema is gRPC's own (gRFC A2 names + A6 retryPolicy)::

      {"methodConfig": [{
           "name": [{"service": "pkg.Svc", "method": "Echo"}],
           "timeout": "1.5s",
           "waitForReady": true,
           "retryPolicy": {"maxAttempts": 4,
                           "initialBackoff": "0.05s",
                           "maxBackoff": "1s",
                           "backoffMultiplier": 2,
                           "retryableStatusCodes": ["UNAVAILABLE"]}}],
       "retryThrottling": {"maxTokens": 10, "tokenRatio": 0.1}}

* name matching precedence is gRPC's: exact service+method, then
  service-wide (no ``method``), then the global default (empty ``{}``).
* an application-supplied ``retry_policy``/call timeout always wins over
  the config (explicit code beats delivered config; for timeouts the
  EFFECTIVE deadline is the min of the two, gRPC's rule).
"""

from __future__ import annotations

import json
import re
import threading
from typing import Dict, List, Optional, Tuple

from tpurpc.rpc.status import StatusCode

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)s$")


def _parse_duration(v) -> float:
    """gRPC JSON duration: ``"1.5s"`` (proto3 JSON form) or a bare number
    of seconds (tolerated for hand-written configs)."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    if isinstance(v, str):
        m = _DURATION_RE.match(v.strip())
        if m:
            return float(m.group(1))
    raise ValueError(f"bad duration {v!r} (want e.g. \"1.5s\")")


class RetryThrottle:
    """Channel-wide retry token bucket (gRFC A6, ``retry_throttle.cc``).

    Every retryable failure costs one token; every success refunds
    ``token_ratio``. Retries are permitted only while the bucket is above
    half — so a backend in collapse stops receiving retry storms even
    though individual calls still carry retry policies."""

    def __init__(self, max_tokens: float, token_ratio: float):
        if max_tokens <= 0 or token_ratio <= 0:
            raise ValueError("maxTokens and tokenRatio must be positive")
        self.max_tokens = float(max_tokens)
        self.token_ratio = float(token_ratio)
        self._tokens = float(max_tokens)
        self._lock = threading.Lock()

    def carry_from(self, prev: "Optional[RetryThrottle]") -> "RetryThrottle":
        """Preserve drain state across config updates (``retry_throttle.cc``
        behavior): a re-resolution re-delivering the config must NOT refill
        the bucket — that would resume a suppressed retry storm on every
        resolver refresh. Same params → adopt the previous token count;
        changed ``maxTokens`` → scale it proportionally."""
        if prev is None:
            return self
        with prev._lock:
            prev_tokens, prev_max = prev._tokens, prev.max_tokens
        with self._lock:
            self._tokens = min(self.max_tokens,
                               prev_tokens * (self.max_tokens / prev_max))
        return self

    def record_failure(self) -> None:
        with self._lock:
            self._tokens = max(0.0, self._tokens - 1.0)

    def record_success(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.token_ratio)

    def allow_retry(self) -> bool:
        with self._lock:
            return self._tokens > self.max_tokens / 2.0

    def tokens(self) -> float:  # observability/test seam
        with self._lock:
            return self._tokens


class MethodConfig:
    """One resolved per-method view: what the channel consults at call time."""

    __slots__ = ("timeout", "retry_policy", "wait_for_ready",
                 "hedging_policy")

    def __init__(self, timeout: Optional[float] = None,
                 retry_policy=None, wait_for_ready: Optional[bool] = None,
                 hedging_policy=None):
        self.timeout = timeout
        self.retry_policy = retry_policy
        self.wait_for_ready = wait_for_ready
        self.hedging_policy = hedging_policy


_EMPTY = MethodConfig()


#: gRPC caps service-config maxAttempts at 5 (retry_service_config.cc
#: clamps with a log line rather than rejecting) — a resolver cannot
#: configure an unbounded retry budget
MAX_ATTEMPTS_CAP = 5


def _parse_retry_policy(body: dict):
    from tpurpc.rpc.channel import RetryPolicy  # lazy: channel imports us

    if not isinstance(body, dict):
        raise ValueError(f"retryPolicy must be an object, got {body!r}")
    codes = []
    for name in body.get("retryableStatusCodes", ()):
        try:
            codes.append(StatusCode[str(name).upper()])
        except KeyError:
            raise ValueError(f"unknown status code {name!r} in "
                             "retryableStatusCodes") from None
    if not codes:
        raise ValueError("retryPolicy needs non-empty retryableStatusCodes")
    max_attempts = int(body.get("maxAttempts", 0))
    if max_attempts < 2:
        raise ValueError("retryPolicy.maxAttempts must be >= 2")
    initial = _parse_duration(body.get("initialBackoff", "0.05s"))
    maxi = _parse_duration(body.get("maxBackoff", "1s"))
    mult = float(body.get("backoffMultiplier", 2.0))
    # zero/negative backoff would be a sleepless hammer loop against a
    # failing backend; the reference rejects these at parse
    if initial <= 0 or maxi <= 0 or mult <= 0:
        raise ValueError("retryPolicy backoff values must be positive")
    return RetryPolicy(
        max_attempts=min(max_attempts, MAX_ATTEMPTS_CAP),
        initial_backoff=initial,
        max_backoff=maxi,
        backoff_multiplier=mult,
        retryable_codes=codes)


def _parse_hedging_policy(body: dict):
    """gRFC A6 ``hedgingPolicy``: N staggered attempts under one deadline.

    Schema (proto3 JSON, like retryPolicy)::

        "hedgingPolicy": {"maxAttempts": 3,
                          "hedgingDelay": "0.01s",
                          "nonFatalStatusCodes": ["UNAVAILABLE"]}

    A status in ``nonFatalStatusCodes`` lets the NEXT hedge fire
    immediately; any other failure is fatal and resolves the call. The
    same ``maxAttempts`` cap as retryPolicy applies."""
    from tpurpc.rpc.channel import HedgingPolicy  # lazy: channel imports us

    if not isinstance(body, dict):
        raise ValueError(f"hedgingPolicy must be an object, got {body!r}")
    codes = []
    for name in body.get("nonFatalStatusCodes", ()):
        try:
            codes.append(StatusCode[str(name).upper()])
        except KeyError:
            raise ValueError(f"unknown status code {name!r} in "
                             "nonFatalStatusCodes") from None
    max_attempts = int(body.get("maxAttempts", 0))
    if max_attempts < 2:
        raise ValueError("hedgingPolicy.maxAttempts must be >= 2")
    delay = _parse_duration(body.get("hedgingDelay", "0s"))
    if delay < 0:
        raise ValueError("hedgingPolicy.hedgingDelay must be >= 0")
    return HedgingPolicy(
        max_attempts=min(max_attempts, MAX_ATTEMPTS_CAP),
        hedging_delay=delay,
        non_fatal_codes=codes or (StatusCode.UNAVAILABLE,))


def split_method(method: str) -> Tuple[str, str]:
    """``"/pkg.Svc/Echo"`` → ``("pkg.Svc", "Echo")`` (tolerates no slash)."""
    path = method.lstrip("/")
    service, _, name = path.rpartition("/")
    return service, name


class ServiceConfig:
    """Parsed service config. Construction VALIDATES (a malformed config is
    rejected whole, like the reference's service_config parse — the channel
    then keeps its previous config rather than half-applying)."""

    def __init__(self, method_configs: List[Tuple[List[Tuple[str, str]],
                                                  MethodConfig]],
                 retry_throttle: Optional[RetryThrottle],
                 raw: dict):
        self._exact: Dict[Tuple[str, str], MethodConfig] = {}
        self._service: Dict[str, MethodConfig] = {}
        self._default: Optional[MethodConfig] = None
        self.retry_throttle = retry_throttle
        self.raw = raw
        for names, mc in method_configs:
            for service, name in names:
                if service and name:
                    self._exact.setdefault((service, name), mc)
                elif service:
                    self._service.setdefault(service, mc)
                else:
                    if self._default is None:
                        self._default = mc

    @classmethod
    def from_json(cls, obj) -> "ServiceConfig":
        if isinstance(obj, (str, bytes)):
            obj = json.loads(obj)
        if not isinstance(obj, dict):
            raise ValueError(f"service config must be an object, got "
                             f"{type(obj).__name__}")
        throttle = None
        if "retryThrottling" in obj:
            rt = obj["retryThrottling"]
            if not isinstance(rt, dict):
                raise ValueError(f"retryThrottling must be an object, "
                                 f"got {rt!r}")
            throttle = RetryThrottle(rt.get("maxTokens", 0),
                                     rt.get("tokenRatio", 0))
        entries: List[Tuple[List[Tuple[str, str]], MethodConfig]] = []
        mc_list = obj.get("methodConfig", ())
        if not isinstance(mc_list, (list, tuple)):
            raise ValueError(f"methodConfig must be a list, got {mc_list!r}")
        for entry in mc_list:
            if not isinstance(entry, dict):
                raise ValueError(f"methodConfig entry must be an object, "
                                 f"got {entry!r}")
            names: List[Tuple[str, str]] = []
            nm_list = entry.get("name", ())
            if not isinstance(nm_list, (list, tuple)):
                raise ValueError(f"methodConfig name must be a list, "
                                 f"got {nm_list!r}")
            for nm in nm_list:
                if not isinstance(nm, dict):
                    raise ValueError(f"methodConfig name entry must be an "
                                     f"object, got {nm!r}")
                service = nm.get("service", "")
                name = nm.get("method", "")
                if name and not service:
                    raise ValueError("method name without service in "
                                     f"methodConfig name {nm!r}")
                names.append((service, name))
            if not names:
                raise ValueError("methodConfig entry without name list")
            if "retryPolicy" in entry and "hedgingPolicy" in entry:
                # gRFC A6: a method has ONE of the two execution strategies
                raise ValueError("methodConfig entry has both retryPolicy "
                                 "and hedgingPolicy (mutually exclusive)")
            mc = MethodConfig(
                timeout=(_parse_duration(entry["timeout"])
                         if "timeout" in entry else None),
                retry_policy=(_parse_retry_policy(entry["retryPolicy"])
                              if "retryPolicy" in entry else None),
                wait_for_ready=entry.get("waitForReady"),
                hedging_policy=(_parse_hedging_policy(entry["hedgingPolicy"])
                                if "hedgingPolicy" in entry else None))
            entries.append((names, mc))
        return cls(entries, throttle, obj)

    def for_method(self, method: str) -> MethodConfig:
        service, name = split_method(method)
        mc = self._exact.get((service, name))
        if mc is not None:
            return mc
        mc = self._service.get(service)
        if mc is not None:
            return mc
        return self._default if self._default is not None else _EMPTY
