"""Channel/server credentials: TLS for every transport, grpcio-shaped.

The reference's security stack (``src/core/lib/security/`` + ``tsi``,
19,417 LoC — SURVEY §2.4) exists so creds work UNCHANGED over the swapped
byte pipe: TLS protects the TCP stream, and on the RDMA platforms it
protects the bootstrap/notify channel while payload rides the registered
rings. tpurpc keeps exactly that split: :func:`ssl_server_credentials` /
:func:`ssl_channel_credentials` build ``ssl.SSLContext`` objects consumed
by the endpoint factory — a TCP connection is wrapped whole; a ring
connection performs its address bootstrap over the TLS socket and keeps it
as the (encrypted) notify/liveness channel, with ring payload staying in
local shm exactly as the reference's stays in registered NIC memory.

API mirrors ``grpc.ssl_server_credentials`` / ``grpc.ssl_channel_credentials``
(src/python/grpcio/grpc/__init__.py) so porting is mechanical.
"""

from __future__ import annotations

import ssl
import tempfile
from typing import Optional, Sequence, Tuple


class ServerCredentials:
    """Opaque server-side credentials (grpcio's ServerCredentials analog)."""

    def __init__(self, context: ssl.SSLContext):
        self._context = context


class ChannelCredentials:
    """Opaque client-side credentials (grpcio's ChannelCredentials analog)."""

    def __init__(self, context: ssl.SSLContext,
                 override_hostname: Optional[str] = None):
        self._context = context
        self._override_hostname = override_hostname


def _load_chain(ctx: ssl.SSLContext, key_pem: bytes, cert_pem: bytes) -> None:
    # ssl only loads cert chains from files; stage the PEMs in a private
    # tempfile pair for the duration of the load.
    with tempfile.NamedTemporaryFile(suffix=".pem") as certf, \
            tempfile.NamedTemporaryFile(suffix=".pem") as keyf:
        certf.write(cert_pem)
        certf.flush()
        keyf.write(key_pem)
        keyf.flush()
        ctx.load_cert_chain(certf.name, keyf.name)


def ssl_server_credentials(
        private_key_certificate_chain_pairs: Sequence[Tuple[bytes, bytes]],
        root_certificates: Optional[bytes] = None,
        require_client_auth: bool = False) -> ServerCredentials:
    """grpcio-shaped: [(private_key_pem, cert_chain_pem)], optional client CA.

    ALPN advertises h2 so stock gRPC-over-TLS clients negotiate cleanly;
    tpurpc-native clients are sniffed after the handshake like on insecure
    ports.
    """
    if not private_key_certificate_chain_pairs:
        raise ValueError("at least one (key, cert-chain) pair required")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    for key_pem, cert_pem in private_key_certificate_chain_pairs:
        _load_chain(ctx, key_pem, cert_pem)
    if root_certificates is not None:
        ctx.load_verify_locations(cadata=root_certificates.decode())
    if require_client_auth:
        if root_certificates is None:
            raise ValueError("require_client_auth needs root_certificates")
        ctx.verify_mode = ssl.CERT_REQUIRED
    elif root_certificates is not None:
        ctx.verify_mode = ssl.CERT_OPTIONAL
    try:
        ctx.set_alpn_protocols(["h2"])
    except NotImplementedError:  # pragma: no cover - openssl without ALPN
        pass
    return ServerCredentials(ctx)


def ssl_channel_credentials(
        root_certificates: Optional[bytes] = None,
        private_key: Optional[bytes] = None,
        certificate_chain: Optional[bytes] = None) -> ChannelCredentials:
    """grpcio-shaped: CA bundle + optional client cert (mTLS)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    if root_certificates is not None:
        ctx.load_verify_locations(cadata=root_certificates.decode())
    else:
        ctx.load_default_certs()
    if private_key is not None and certificate_chain is not None:
        _load_chain(ctx, private_key, certificate_chain)
    try:
        ctx.set_alpn_protocols(["h2"])
    except NotImplementedError:  # pragma: no cover
        pass
    return ChannelCredentials(ctx)


def insecure_for_testing_channel_credentials() -> ChannelCredentials:
    """TLS without certificate verification — tests and lab rigs ONLY (the
    grpc.ssl_target_name_override moral equivalent, minus the hostname)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    try:
        ctx.set_alpn_protocols(["h2"])
    except NotImplementedError:  # pragma: no cover
        pass
    return ChannelCredentials(ctx)
