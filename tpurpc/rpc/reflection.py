"""gRPC Server Reflection (grpc.reflection.v1alpha + v1) for tpurpc servers.

The standard tooling hook — ``grpcurl list``, ``grpc_cli ls``, IDE explorers
— speaks a bidi stream of ``ServerReflectionRequest``/``Response`` messages
(ref ``src/cpp/ext/proto_server_reflection.cc``; proto at
``src/proto/grpc/reflection/v1alpha/reflection.proto``). tpurpc implements
the wire format by hand like :mod:`tpurpc.rpc.health` does — the handful of
fields involved don't justify a protobuf dependency:

    ServerReflectionRequest {
      string host = 1;
      oneof message_request {
        string file_by_filename = 3;
        string file_containing_symbol = 4;
        ExtensionRequest file_containing_extension = 5;
        string all_extension_numbers_of_type = 6;
        string list_services = 7;
      }
    }
    ServerReflectionResponse {
      string valid_host = 1;
      ServerReflectionRequest original_request = 2;
      oneof message_response {
        FileDescriptorResponse file_descriptor_response = 4;   // repeated bytes fdp = 1
        ExtensionNumberResponse all_extension_numbers_response = 5;
        ListServiceResponse list_services_response = 6;        // repeated ServiceResponse{name=1} = 1
        ErrorResponse error_response = 7;                      // int32 code = 1, string msg = 2
      }
    }

``list_services`` is answered from the server's registered method table (the
part every tool needs); descriptor lookups are answered from an optional
registry filled via :func:`ServerReflection.add_file_descriptor_protos`
(serialized ``FileDescriptorProto`` bytes, e.g. from generated
``*_pb2.DESCRIPTOR.serialized_pb``) and return NOT_FOUND otherwise, exactly
like a C++ server built without the descriptor pool entries.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

from tpurpc.wire.protowire import encode_varint as _varint
from tpurpc.wire.protowire import fields as _fields
from tpurpc.wire.protowire import ld as _ld

V1ALPHA_SERVICE = "grpc.reflection.v1alpha.ServerReflection"
V1_SERVICE = "grpc.reflection.v1.ServerReflection"


class _Request:
    """Decoded ServerReflectionRequest (which_oneof, argument)."""

    ONEOF = {3: "file_by_filename", 4: "file_containing_symbol",
             5: "file_containing_extension",
             6: "all_extension_numbers_of_type", 7: "list_services"}

    def __init__(self, raw: bytes):
        self.raw = bytes(raw)
        self.host = ""
        self.kind: Optional[str] = None
        self.arg = b""
        for field_no, wt, val in _fields(self.raw):
            if field_no == 1:
                self.host = bytes(val).decode("utf-8", "replace")
            elif field_no in self.ONEOF:
                if wt != 2:
                    # every oneof arm is a string/message: length-delimited
                    # only. A varint here is a malformed request, not a
                    # lookup that happens to miss.
                    raise ValueError(
                        f"oneof field {field_no} has wire type {wt}")
                self.kind = self.ONEOF[field_no]
                self.arg = bytes(val)


#: process-global descriptor registry: generated ``*_tpurpc.py`` modules
#: register their pb2 files at import, so every reflection servicer created
#: afterwards can answer describe/file_containing_symbol with no manual
#: wiring (grpcio gets this from the protobuf descriptor pool; this is the
#: explicit tpurpc equivalent)
_GLOBAL_FILES: list = []
_GLOBAL_LOCK = threading.Lock()


def register_module_descriptors(serialized) -> None:
    """Called by generated modules: add serialized FileDescriptorProtos to
    the process-global registry (idempotent by content)."""
    with _GLOBAL_LOCK:
        for raw in serialized:
            raw = bytes(raw)
            if raw not in _GLOBAL_FILES:
                _GLOBAL_FILES.append(raw)


class ServerReflection:
    """The servicer. Attach with :func:`enable_server_reflection`."""

    NOT_FOUND = 5  # grpc status code carried in ErrorResponse.error_code

    def __init__(self, server: Server):
        self._server = server
        self._lock = threading.Lock()
        #: filename -> serialized FileDescriptorProto
        self._files: Dict[str, bytes] = {}
        #: symbol (pkg.Msg / pkg.Svc / pkg.Svc.Method) -> filename
        self._symbols: Dict[str, str] = {}
        with _GLOBAL_LOCK:
            seed = list(_GLOBAL_FILES)
        if seed:
            self.add_file_descriptor_protos(seed)

    # -- descriptor registry -------------------------------------------------

    def add_file_descriptor_protos(self, serialized: List[bytes]) -> None:
        """Register serialized FileDescriptorProtos (e.g.
        ``mod_pb2.DESCRIPTOR.serialized_pb``) for descriptor lookups."""
        for raw in serialized:
            name, symbols = _index_fdp(raw)
            with self._lock:
                self._files[name] = bytes(raw)
                for s in symbols:
                    self._symbols[s] = name

    # -- service list --------------------------------------------------------

    def _service_names(self) -> List[str]:
        names = set()
        for path in self._server._methods:
            #  "/pkg.Service/Method" -> "pkg.Service"
            svc = path.rsplit("/", 1)[0].lstrip("/")
            if svc:
                names.add(svc)
        return sorted(names)

    # -- the RPC -------------------------------------------------------------

    def _info(self, request_iterator: Iterator[bytes], ctx) -> Iterator[bytes]:
        for raw in request_iterator:
            try:
                req = _Request(raw)
            except ValueError:
                yield _ld(7, _varint((1 << 3) | 0) + _varint(3)  # INVALID_ARG
                          + _ld(2, b"malformed ServerReflectionRequest"))
                continue
            body = self._answer(req)
            # valid_host(1) + original_request(2) + the answer
            yield (_ld(1, req.host.encode()) + _ld(2, req.raw) + body)

    def _answer(self, req: _Request) -> bytes:
        if req.kind == "list_services":
            services = b"".join(
                _ld(1, _ld(1, name.encode()))        # ServiceResponse.name
                for name in self._service_names())
            return _ld(6, services)                   # list_services_response
        if req.kind in ("file_by_filename", "file_containing_symbol"):
            key = req.arg.decode("utf-8", "replace")
            with self._lock:
                if req.kind == "file_by_filename":
                    raw = self._files.get(key)
                else:
                    raw = self._files.get(self._symbols.get(key, ""))
            if raw is not None:
                return _ld(4, _ld(1, raw))            # file_descriptor_response
            return self._error(f"{req.kind} not found: {key!r}")
        if req.kind == "all_extension_numbers_of_type":
            return self._error("extensions not supported")
        if req.kind == "file_containing_extension":
            return self._error("extensions not supported")
        return self._error("no message_request set")

    def _error(self, msg: str) -> bytes:
        return _ld(7, bytes([1 << 3]) + _varint(self.NOT_FOUND)
                   + _ld(2, msg.encode()))


def _index_fdp(raw: bytes):
    """Minimal FileDescriptorProto scan: name(1), package(2),
    message_type(4).name(1), service(6){name(1), method(2).name(1)}."""
    name = ""
    package = ""
    messages: List[str] = []
    services: List[tuple] = []
    for field_no, _wt, val in _fields(bytes(raw)):
        if field_no == 1:
            name = bytes(val).decode()
        elif field_no == 2:
            package = bytes(val).decode()
        elif field_no == 4:  # DescriptorProto
            for f2, _w2, v2 in _fields(bytes(val)):
                if f2 == 1:
                    messages.append(bytes(v2).decode())
                    break
        elif field_no == 6:  # ServiceDescriptorProto
            sname, methods = "", []
            for f2, _w2, v2 in _fields(bytes(val)):
                if f2 == 1:
                    sname = bytes(v2).decode()
                elif f2 == 2:  # MethodDescriptorProto
                    for f3, _w3, v3 in _fields(bytes(v2)):
                        if f3 == 1:
                            methods.append(bytes(v3).decode())
                            break
            services.append((sname, methods))
    prefix = package + "." if package else ""
    symbols = [prefix + m for m in messages]
    for sname, methods in services:
        symbols.append(prefix + sname)
        symbols.extend(f"{prefix}{sname}.{m}" for m in methods)
    return name, symbols


def enable_server_reflection(server: Server) -> ServerReflection:
    """Attach reflection under both the v1alpha and v1 service names
    (grpcurl probes v1 first, falls back to v1alpha)."""
    servicer = ServerReflection(server)
    handler = stream_stream_rpc_method_handler(servicer._info)
    for svc in (V1ALPHA_SERVICE, V1_SERVICE):
        server.add_method(f"/{svc}/ServerReflectionInfo", handler)
    return servicer
