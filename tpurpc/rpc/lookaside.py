"""Look-aside load balancing — the grpclb capability, tpurpc-shaped.

The reference ships ``lb_policy/grpclb/grpclb.cc``: the channel opens a
stream to a BALANCER service, receives ServerList updates, directs RPCs at
the listed backends, and falls back to its resolver-provided addresses if
the balancer is unreachable (fallback timer). This module is that control
loop over tpurpc's own streaming RPC + :meth:`Channel.update_addresses`:

server side::

    balancer = LoadBalancerServicer()
    balancer.attach(admin_server)                 # serves /tpurpc.lb.v1.*
    balancer.set_servers("inventory", ["10.0.0.5:50051", "10.0.0.6:50051"])

client side::

    ch = rpc.Channel("fallback-host:50051", lb_policy="round_robin")
    watcher = enable_lookaside(ch, "balancer-host:9000", name="inventory")
    ...                                            # calls rebalance live
    watcher.stop()

Two wire formats. Native: JSON bodies on ``/tpurpc.lb.v1.LoadBalancer/
BalanceLoad`` (request ``{"name": ...}``, responses ``{"servers":
["h:p", ...]}``). Standard: the grpc.lb.v1 protobuf stream
(:mod:`tpurpc.rpc.lb_v1`) — ``attach`` serves both, and
``enable_lookaside(..., wire="grpclb")`` consumes a stock balancer.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence

from tpurpc.rpc.status import RpcError
from tpurpc.utils.trace import TraceFlag

trace_lb = TraceFlag("lookaside")

SERVICE = "tpurpc.lb.v1.LoadBalancer"
METHOD = f"/{SERVICE}/BalanceLoad"


class LoadBalancerServicer:
    """Balancer service: per-name server lists, pushed to subscribers.

    ``set_servers(name, addrs)`` updates a list and wakes every watcher
    stream; each stream immediately receives the current list on
    subscribe (grpclb's initial ServerList)."""

    def __init__(self, stats_interval_s: float = 0.0):
        self._lock = threading.Condition()
        self._lists: Dict[str, List[str]] = {}
        self._epoch = 0
        #: >0 asks grpc.lb.v1 subscribers to stream ClientStats load
        #: reports on this cadence (the grpclb load-reporting loop)
        self._stats_interval_s = stats_interval_s
        self._client_stats: Dict[str, Dict[str, int]] = {}

    def stats(self, name: str) -> Dict[str, int]:
        """Accumulated ClientStats deltas reported by grpc.lb.v1
        subscribers of ``name`` (empty until a report arrives)."""
        with self._lock:
            return dict(self._client_stats.get(name, {}))

    def _record_stats(self, name: str, report: Dict[str, int]) -> None:
        with self._lock:
            acc = self._client_stats.setdefault(
                name, {"started": 0, "finished": 0, "known_received": 0})
            for key, val in report.items():
                acc[key] = acc.get(key, 0) + val

    def set_servers(self, name: str, addrs: Sequence[str]) -> None:
        with self._lock:
            self._lists[name] = list(addrs)
            self._epoch += 1
            self._lock.notify_all()

    def _updates(self, name: str, ctx):
        """Yield the current list for ``name``, then every change, until
        the stream dies — shared by both wire formats."""
        last_sent: Optional[List[str]] = None
        while ctx.is_active():
            with self._lock:
                current = list(self._lists.get(name, []))
                epoch = self._epoch
                if current == last_sent:
                    # wait for a change (bounded so ctx liveness re-checks)
                    self._lock.wait_for(lambda: self._epoch != epoch,
                                        timeout=1.0)
                    continue
            last_sent = current
            yield current

    def _balance_load(self, request_iterator, ctx):
        first = next(iter(request_iterator), None)
        if first is None:
            return
        try:
            name = json.loads(bytes(first).decode())["name"]
        except (ValueError, KeyError):
            from tpurpc.rpc.status import AbortError, StatusCode

            raise AbortError(StatusCode.INVALID_ARGUMENT,
                             "malformed BalanceLoad request") from None
        for current in self._updates(name, ctx):
            yield json.dumps({"servers": current}).encode()

    def _balance_load_v1(self, request_iterator, ctx):
        """The stock grpc.lb.v1 wire (tpurpc.rpc.lb_v1): initial_response
        first (optionally requesting ClientStats reports), then a
        ServerList per change — what a stock grpclb client expects from
        its balancer. Incoming ClientStats are drained on a side thread
        (the update loop must not block on a quiet client)."""
        from tpurpc.rpc import lb_v1

        it = iter(request_iterator)
        first = next(it, None)
        if first is None:
            return
        try:
            name = lb_v1.decode_request(first)
        except ValueError:  # malformed protobuf, not a handler bug
            name = None
        if name is None:
            from tpurpc.rpc.status import AbortError, StatusCode

            raise AbortError(StatusCode.INVALID_ARGUMENT,
                             "BalanceLoad stream must open with "
                             "initial_request") from None

        def drain_reports():
            for msg in it:
                try:
                    report = lb_v1.decode_client_stats(msg)
                except ValueError:
                    continue
                if report:
                    self._record_stats(name, report)

        threading.Thread(target=drain_reports, daemon=True,
                         name="tpurpc-lb-stats").start()
        yield lb_v1.encode_initial_response(self._stats_interval_s)
        for current in self._updates(name, ctx):
            yield lb_v1.encode_server_list(current)

    def attach(self, server) -> None:
        """Registers BOTH wires: the tpurpc-native JSON method and the
        standard grpc.lb.v1 protobuf method."""
        from tpurpc.rpc import lb_v1
        from tpurpc.rpc.server import stream_stream_rpc_method_handler

        server.add_method(METHOD,
                          stream_stream_rpc_method_handler(self._balance_load))
        server.add_method(
            lb_v1.METHOD,
            stream_stream_rpc_method_handler(self._balance_load_v1))


class LookasideWatcher:
    """The client control loop: subscribe, apply updates, fall back."""

    def __init__(self, channel, balancer_target: str, name: str,
                 fallback_timeout: float = 10.0, wire: str = "tpurpc"):
        if wire not in ("tpurpc", "grpclb"):
            raise ValueError(f"unknown look-aside wire {wire!r} "
                             "(tpurpc | grpclb)")
        self._wire = wire
        if getattr(channel, "_addrs", None) is None:
            # fail fast: endpoint_factory channels have fixed membership;
            # discovering this on the first ServerList would kill the
            # watcher thread silently
            raise ValueError(
                "look-aside balancing needs a target-built channel "
                "(endpoint_factory channels have fixed membership)")
        self._channel = channel
        self._balancer_target = balancer_target
        self._name = name
        self._fallback_timeout = fallback_timeout
        #: the resolver-provided addresses to fall back to (grpclb fallback)
        self._fallback_addrs = list(channel._addrs)
        self._stop = threading.Event()
        self._applied_balancer_list = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpurpc-lookaside")
        self._thread.start()

    def _run(self) -> None:
        from tpurpc.rpc.channel import Channel

        backoff = 0.2
        while not self._stop.is_set():
            try:
                with Channel(self._balancer_target,
                             connect_timeout=self._fallback_timeout) as bch:
                    self._bch = bch  # stop() closes it to unblock the recv
                    if self._wire == "grpclb":
                        from tpurpc.rpc import lb_v1

                        method = lb_v1.METHOD
                        sub = lb_v1.encode_initial_request(self._name)
                    else:
                        method = METHOD
                        sub = json.dumps({"name": self._name}).encode()
                    stream = bch.stream_stream(method)
                    self._stats_interval = 0.0  # set by initial_response

                    def reqs():
                        yield sub
                        # hold the stream open until stop; on the grpclb
                        # wire, stream ClientStats DELTAS whenever the
                        # balancer's initial_response requested a cadence
                        # (grpclb load reporting). Baseline from the
                        # CURRENT counters: a reconnected stream must not
                        # re-report the channel's lifetime totals.
                        cc = self._channel.call_counters
                        last = (cc.started, cc.succeeded + cc.failed,
                                cc.succeeded)
                        next_report: Optional[float] = None
                        while not self._stop.wait(0.2):
                            interval = self._stats_interval
                            if self._wire != "grpclb" or interval <= 0:
                                continue
                            now = time.monotonic()
                            if next_report is None:
                                next_report = now + interval
                            if now < next_report:
                                continue
                            next_report = now + interval
                            from tpurpc.rpc import lb_v1

                            cur = (cc.started, cc.succeeded + cc.failed,
                                   cc.succeeded)
                            delta = tuple(c - l for c, l in zip(cur, last))
                            last = cur
                            # known_received = SUCCEEDED only: failed calls
                            # never reached a server and must read as loss
                            # to a balancer computing finished - received
                            yield lb_v1.encode_client_stats(
                                delta[0], delta[1], delta[2])
                        return

                    for msg in stream(reqs(), timeout=None):
                        if self._stop.is_set():
                            return
                        if self._wire == "grpclb":
                            from tpurpc.rpc import lb_v1

                            try:
                                kind, servers = lb_v1.decode_response(msg)
                            except ValueError:
                                # one bad message must not tear the stream
                                # down (the JSON path skips these too)
                                trace_lb.log("undecodable LoadBalanceResponse"
                                             " skipped")
                                continue
                            if kind == "initial":
                                self._stats_interval = float(servers or 0.0)
                                continue
                            if kind in ("fallback", "unknown"):
                                continue
                        else:
                            try:
                                servers = json.loads(
                                    bytes(msg).decode()).get("servers")
                            except ValueError:
                                servers = None
                        if not servers:
                            trace_lb.log("ignoring malformed/empty "
                                         "ServerList update")
                            continue
                        if servers:
                            trace_lb.log("lookaside %r -> %d servers",
                                         self._name, len(servers))
                            self._channel.update_addresses(servers)
                            self._applied_balancer_list = True
                        backoff = 0.2
            except (RpcError, OSError, ValueError) as exc:
                trace_lb.log("balancer stream failed: %s", exc)
            if self._stop.is_set():
                return
            # balancer unreachable: restore the fallback list once
            # (grpclb fallback-to-resolver rule), then retry with backoff
            if self._applied_balancer_list and self._fallback_addrs:
                try:
                    self._channel.update_addresses(self._fallback_addrs)
                    self._applied_balancer_list = False
                    trace_lb.log("lookaside %r: fell back to resolver list",
                                 self._name)
                except (RpcError, RuntimeError):
                    pass  # channel closing
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, 5.0)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        bch = getattr(self, "_bch", None)
        if bch is not None:
            try:
                bch.close()  # unblocks a watcher parked in recv
            except Exception:
                pass
        self._thread.join(timeout=timeout)


def enable_lookaside(channel, balancer_target: str, name: str,
                     fallback_timeout: float = 10.0,
                     wire: str = "tpurpc") -> LookasideWatcher:
    """Attach a grpclb-style watcher to ``channel``; returns the watcher
    (call ``stop()`` before closing the channel). ``wire="grpclb"``
    speaks the standard grpc.lb.v1 protobuf stream (stock balancers);
    the default speaks the tpurpc-native JSON protocol."""
    return LookasideWatcher(channel, balancer_target, name,
                            fallback_timeout, wire)
