"""tpurpc native wire format: multiplexed frames over one byte-pipe endpoint.

Design position (SURVEY.md §7 stage 3): the reference rides unmodified HTTP/2
(``src/core/ext/transport/chttp2/``, 15,302 LoC) above its swapped byte pipe.  tpurpc
keeps the *semantics* HTTP/2 gives gRPC — stream multiplexing, metadata, half-close,
trailers-carry-status, cancellation (RST_STREAM), ping — in a deliberately simpler
binary framing, because HPACK + h2 flow-control windows buy nothing on a
single-tenant accelerator-to-accelerator link.  A separate ``tpurpc.rpc.h2`` module
speaks true gRPC-over-HTTP/2 for stock-grpcio interop; both sit on the same Endpoint.

Frame layout (all integers little-endian)::

    [u8 type][u8 flags][u32 stream_id][u32 length] [payload: length bytes]

Frame types mirror the h2 subset gRPC actually uses (``frame_*.cc`` in the
reference): HEADERS, MESSAGE (DATA), TRAILERS (HEADERS+END_STREAM), RST, PING,
GOAWAY.  A MESSAGE larger than ``MAX_FRAME_PAYLOAD`` is split into fragments with
the MORE flag set on all but the last — the structural analog of the reference's
chunked flush at ``max_send_size`` (``rdma_event_posix.cc:312-421``).

Metadata encoding: ``u16 count`` then per-entry ``u16 keylen, key-utf8,
u32 vallen, value-bytes``.  Keys ending in ``-bin`` carry binary values (gRPC
convention); all other values are utf-8 text.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

from tpurpc.core.endpoint import Endpoint
from tpurpc.obs import profiler as _profiler
from tpurpc.rpc.status import StatusCode
from tpurpc.tpu import ledger as _ledger

# tpurpc-lens (ISSUE 8): native-framing encode + the coalescing writev
# flusher are h2-framing-stage work for the sampling profiler
_LENS_STAGES = {
    "send": "h2-framing",
    "send_many": "h2-framing",
    "_send_fragmented": "h2-framing",
    "_flush_pending": "h2-framing",
    "encode_frame": "h2-framing",
}
_profiler.register_stages(__file__, _LENS_STAGES)

MAGIC = b"TPURPC\x01\x00"  # connection preface, client → server
MAX_FRAME_PAYLOAD = 1 << 20
HEADER_FMT = struct.Struct("<BBII")

# frame types
HEADERS = 1
MESSAGE = 2
TRAILERS = 3
RST = 4
PING = 5
PONG = 6
GOAWAY = 7
# tpurpc-express (ISSUE 9) rendezvous control frames: the bulk payload
# itself never rides a frame — it is one-sided-written into the receiver's
# advertised landing region; these tiny control messages are all the framed
# connection carries for a rendezvous'd MESSAGE. Only sent after the PING
# capability hello proved the peer speaks them (core/rendezvous.py).
RDV_OFFER = 8
RDV_CLAIM = 9
RDV_COMPLETE = 10
RDV_RELEASE = 11
# tpurpc-pulse (ISSUE 13): one-shot wake for a PARKED descriptor-ring
# consumer — the only frame a cold→hot control-plane transition costs.
# Carries nothing; the receiver's read loop drains its ring on every
# wakeup, so the frame's arrival IS the delivery. Only ever sent to a
# peer that advertised a ring in the hello (same-build guarantee).
CTRL_KICK = 12

#: canonical rendezvous op <-> native frame type (ops are transport-
#: agnostic small ints; the h2 planes carry them in an extension frame)
RDV_FRAME_OF_OP = {1: RDV_OFFER, 2: RDV_CLAIM, 3: RDV_COMPLETE,
                   4: RDV_RELEASE}
RDV_OP_OF_FRAME = {v: k for k, v in RDV_FRAME_OF_OP.items()}

# flags
FLAG_END_STREAM = 0x01  # sender half-closes this stream (ref: h2 END_STREAM)
FLAG_MORE = 0x02        # this MESSAGE frame is a fragment; more follow
FLAG_COMPRESSED = 0x08  # MESSAGE payload is gzip-compressed (whole message;
#                         set on every fragment). Senders request it by
#                         passing the flag to FrameWriter, which performs
#                         the compression — receivers gunzip at reassembly.
#                         The gRPC wire's per-message compressed-flag
#                         (grpc-encoding) recast for the tpurpc framing.
FLAG_REFUSED = 0x10     # RST only: stream refused at admission — no handler ran,
                        # replay on a fresh connection is safe (h2 REFUSED_STREAM;
                        # C mirror: framing_common.h kFlagRefused)
FLAG_NO_MESSAGE = 0x04  # MESSAGE frame carries no message (pure half-close marker),
                        # distinguishing it from a genuine empty message

#: Sentinel substring in the UNIMPLEMENTED trailer a decompressor-less peer
#: sends when rejecting a FLAG_COMPRESSED stream. The channel's compression
#: negotiation (degrade-to-identity + transparent unary replay) keys on it,
#: so it MUST stay a substring of the native peers' wordings:
#: native/src/tpurpc_server.cc ("compressed messages unsupported here") and
#: native/src/tpurpc_client.cc ("... unsupported by the native client").
COMPRESSED_UNSUPPORTED_SENTINEL = "compressed messages unsupported"

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


class FrameError(Exception):
    """Protocol violation on the wire; connection-fatal."""


def encode_metadata(md: Sequence[Tuple[str, "str | bytes"]]) -> bytes:
    parts = [_U16.pack(len(md))]
    for key, value in md:
        kb = key.encode("utf-8")
        vb = value if isinstance(value, (bytes, bytearray)) else str(value).encode("utf-8")
        parts.append(_U16.pack(len(kb)))
        parts.append(kb)
        parts.append(_U32.pack(len(vb)))
        parts.append(bytes(vb))
    return b"".join(parts)


def decode_metadata(buf: bytes, offset: int = 0) -> Tuple[List[Tuple[str, "str | bytes"]], int]:
    try:
        (count,) = _U16.unpack_from(buf, offset)
        offset += 2
        out: List[Tuple[str, "str | bytes"]] = []
        for _ in range(count):
            (klen,) = _U16.unpack_from(buf, offset)
            offset += 2
            key = bytes(buf[offset:offset + klen]).decode("utf-8")
            offset += klen
            (vlen,) = _U32.unpack_from(buf, offset)
            offset += 4
            raw = bytes(buf[offset:offset + vlen])
            offset += vlen
            value: "str | bytes" = raw if key.endswith("-bin") else raw.decode("utf-8")
            out.append((key, value))
        return out, offset
    except (struct.error, UnicodeDecodeError) as exc:
        raise FrameError(f"bad metadata block: {exc}") from exc


class Frame:
    __slots__ = ("type", "flags", "stream_id", "payload")

    def __init__(self, type: int, flags: int, stream_id: int, payload: bytes = b""):
        self.type = type
        self.flags = flags
        self.stream_id = stream_id
        self.payload = payload

    def __repr__(self) -> str:
        names = {1: "HEADERS", 2: "MESSAGE", 3: "TRAILERS", 4: "RST",
                 5: "PING", 6: "PONG", 7: "GOAWAY", 8: "RDV_OFFER",
                 9: "RDV_CLAIM", 10: "RDV_COMPLETE", 11: "RDV_RELEASE",
                 12: "CTRL_KICK"}
        return (f"<Frame {names.get(self.type, self.type)} sid={self.stream_id} "
                f"flags={self.flags:#x} len={len(self.payload)}>")


def encode_frame(ftype: int, flags: int, stream_id: int, payload: bytes = b"") -> List[bytes]:
    """Header + payload as separate slices for the endpoint's gather write."""
    out = [HEADER_FMT.pack(ftype, flags, stream_id, len(payload))]
    if payload:
        out.append(payload)
    return out


def headers_payload(path: str, metadata: Sequence[Tuple[str, "str | bytes"]] = (),
                    timeout_us: Optional[int] = None) -> bytes:
    md = [(":path", path)]
    if timeout_us is not None:
        md.append((":timeout-us", str(timeout_us)))
    md.extend(metadata)
    return encode_metadata(md)


def parse_headers(payload: bytes) -> Tuple[str, Optional[int], List[Tuple[str, "str | bytes"]]]:
    md, _ = decode_metadata(payload)
    path = ""
    timeout_us: Optional[int] = None
    user: List[Tuple[str, "str | bytes"]] = []
    for key, value in md:
        if key == ":path":
            path = str(value)
        elif key == ":timeout-us":
            try:
                timeout_us = int(value)
            except ValueError as exc:
                raise FrameError(f"bad :timeout-us {value!r}") from exc
        else:
            user.append((key, value))
    if not path:
        raise FrameError("HEADERS missing :path")
    return path, timeout_us, user


MAX_STATUS_DETAILS = 16 << 10


def trailers_payload(code: StatusCode, details: str = "",
                     metadata: Sequence[Tuple[str, "str | bytes"]] = ()) -> bytes:
    md = [(":status", str(int(code)))]
    if details:
        # Bound the status message (e.g. a handler exception repr) so trailers
        # always fit one control frame.
        md.append((":message", details[:MAX_STATUS_DETAILS]))
    md.extend(metadata)
    return encode_metadata(md)


def parse_trailers(payload: bytes) -> Tuple[StatusCode, str, List[Tuple[str, "str | bytes"]]]:
    md, _ = decode_metadata(payload)
    code = StatusCode.UNKNOWN
    details = ""
    user: List[Tuple[str, "str | bytes"]] = []
    for key, value in md:
        if key == ":status":
            try:
                code = StatusCode(int(value))
            except ValueError as exc:
                raise FrameError(f"bad :status {value!r}") from exc
        elif key == ":message":
            details = str(value)
        else:
            user.append((key, value))
    return code, details, user


def rst_payload(code: StatusCode, details: str = "") -> bytes:
    return trailers_payload(code, details)


parse_rst = parse_trailers


def _compress_segs(segs, total):
    """gzip a MESSAGE payload (FLAG_COMPRESSED contract: the WHOLE message
    is one gzip stream; fragmentation happens after). Returns the segs
    unchanged with ``compressed=False`` when gzip would ENLARGE the
    payload (incompressible data: the gRPC wire clears its per-message
    compressed bit the same way)."""
    import gzip

    joined = b"".join(bytes(s) for s in segs)
    out = gzip.compress(joined, compresslevel=1)  # speed over ratio: this
    # sits on the RPC hot path; level 1 still collapses repetitive tensors
    if len(out) >= total:
        return segs, total, False
    return [memoryview(out)], len(out), True


class DecompressTooLarge(FrameError):
    """FLAG_COMPRESSED payload inflates past the receive limit (a
    gzip-bomb guard — gRPC enforces max_receive_message_length on the
    POST-decompression size, and so do we)."""


def decompress_message(data, limit: "int | None" = None) -> bytes:
    """Receiver-side inverse of FLAG_COMPRESSED. Raises
    :class:`DecompressTooLarge` when the inflated size exceeds ``limit``,
    :class:`FrameError` on a payload that does not gunzip (protocol
    violation, not app data)."""
    import zlib

    d = zlib.decompressobj(31)  # 31 = gzip wrapper
    try:
        if limit is None or limit < 0:  # None/-1 both mean "unlimited"
            out = d.decompress(bytes(data))
        else:
            out = d.decompress(bytes(data), max(1, limit) + 1)
            if len(out) > limit or d.unconsumed_tail:
                raise DecompressTooLarge(
                    f"compressed message inflates past the receive "
                    f"limit ({limit} bytes)")
        if not d.eof:
            raise FrameError("FLAG_COMPRESSED payload is a truncated "
                             "gzip stream")
        return out
    except zlib.error as exc:
        raise FrameError(f"FLAG_COMPRESSED payload does not gunzip: {exc}"
                         ) from exc


class FrameWriter:
    """Serializes frame writes from many threads onto one endpoint.

    The single lock is the moral equivalent of chttp2's write-combiner
    (``chttp2_transport.cc:997`` write_action): one writer at a time, gather slices,
    large messages fragmented so no stream can monopolize the pipe.

    With ``coalesce=True`` (the server's response path, ISSUE 3),
    ``send_many`` becomes a cross-stream write combiner: responses
    completing close together on one connection flush as ONE gathered
    writev — one transport write/notify for N streams' responses instead
    of N. The flush window is self-clocking: while one thread's writev is
    in flight, later responses queue and the flusher drains them in its
    next writev, so an idle connection pays zero added latency (no timer)
    and a busy one amortizes wakeups. ``max_coalesce_bytes`` caps a single
    gathered writev; the remainder flushes in the next one. Plain
    ``send`` and the fragmenting path stay direct — per-stream frame order
    is preserved because a unary stream's fused response is its only
    coalesced write.
    """

    #: cap on one coalesced writev (gather-list growth bound); responses
    #: past it flush in the flusher's next writev
    MAX_COALESCE_BYTES = 256 << 10

    def __init__(self, endpoint: Endpoint, coalesce: bool = False,
                 max_coalesce_bytes: Optional[int] = None):
        import threading

        self._ep = endpoint
        self._lock = threading.Lock()
        #: tpurpc-pulse: frames this writer has committed to the wire, in
        #: order.  Descriptor-ring control records stamp this count at post
        #: time so the receiver can order them against in-flight frames
        #: (core/ctrlring.py frame_seq gate).  Guarded by its own lock —
        #: bumps happen under _lock on some paths and _pend_lock on others.
        self.frames_sent = 0
        self._fs_lock = threading.Lock()
        #: per-thread frame batch (FrameWriter.batch): frames queue here
        #: and flush as ONE gathered writev at context exit — the
        #: coalesced control path for bursts of small control RPCs
        self._tls = threading.local()
        #: tpurpc-express: the connection's rendezvous link, bound by the
        #: owning connection once constructed. When set, MESSAGE payloads
        #: over the size bar are moved by a one-sided write into the
        #: peer's landing region instead of fragmented frames; everything
        #: below the bar (and every control frame) keeps this path.
        self.rdv = None
        self._coalesce = coalesce
        self._max_coalesce = max_coalesce_bytes or self.MAX_COALESCE_BYTES
        self._pend_lock = threading.Lock()
        #: queued coalescable writes: (nbytes, [segs]) — appended when a
        #: flush is in flight; drained by the flusher (FIFO, so one
        #: stream's queued writes can never reorder)
        self._pending: List = []
        self._flushing = False

    def send(self, ftype: int, flags: int, stream_id: int,
             payload: "bytes | Sequence" = b"") -> None:
        """Write one logical frame.

        MESSAGE payloads may be a gather list of buffers (the tensor codec's
        segment output) — they are fragmented and scatter-written with zero
        joins/copies; the endpoint's gather write (ring slice-send /
        ``sendmsg``) does the placement.
        """
        segs = ([memoryview(s).cast("B") for s in payload]
                if isinstance(payload, (list, tuple)) else
                [memoryview(payload).cast("B")])
        segs = [s for s in segs if len(s)]
        total = sum(len(s) for s in segs)
        rdv = self.rdv
        if (rdv is not None and ftype == MESSAGE and total
                and not (flags & (FLAG_NO_MESSAGE | FLAG_MORE))
                and rdv.eligible(total,
                                 flags_compressed=bool(
                                     flags & FLAG_COMPRESSED))
                and rdv.send_message(stream_id, flags, segs, total)):
            return  # payload one-sided-written; COMPLETE already framed
        if ftype == MESSAGE and flags & FLAG_COMPRESSED:
            segs, total, did = _compress_segs(segs, total)
            if not did:  # incompressible: send as-is, clear the bit
                flags &= ~FLAG_COMPRESSED
        if total <= MAX_FRAME_PAYLOAD:
            tb = getattr(self._tls, "batch", None)
            if tb is not None:
                tb[1].append(memoryview(
                    HEADER_FMT.pack(ftype, flags, stream_id, total)))
                tb[1].extend(segs)
                tb[0] += 1
                self._count_frames(1)
                return
            with self._lock:
                self._ep.write(
                    [HEADER_FMT.pack(ftype, flags, stream_id, total)] + segs)
            self._count_frames(1)
            return
        self._flush_thread_batch()  # oversized frame: preserve order
        if ftype != MESSAGE:
            # Control frames don't fragment; sending one oversized would make
            # the peer tear down the whole multiplexed connection.  Fail just
            # this caller instead.
            raise FrameError(
                f"control frame payload {total} exceeds "
                f"{MAX_FRAME_PAYLOAD}; metadata too large")
        self._send_fragmented(flags, stream_id, segs, total)

    def _send_fragmented(self, flags: int, stream_id: int,
                         segs: List[memoryview], total: int) -> None:
        # Lock per fragment, not per message: fragments carry stream_id +
        # FLAG_MORE so other streams' frames (and PING/PONG, TRAILERS) may
        # interleave — a huge tensor on a credit-stalled ring must not add
        # head-of-line latency to every other stream on the connection.
        sent = 0
        si = 0       # current segment index
        so = 0       # offset within current segment
        while sent < total:
            n = min(MAX_FRAME_PAYLOAD, total - sent)
            frame_segs: List[memoryview] = []
            need = n
            while need:
                seg = segs[si]
                take = min(need, len(seg) - so)
                frame_segs.append(seg[so:so + take])
                so += take
                need -= take
                if so == len(seg):
                    si += 1
                    so = 0
            sent += n
            last = sent >= total
            fl = (flags if last else (flags & ~FLAG_END_STREAM) | FLAG_MORE)
            with self._lock:
                self._ep.write(
                    [HEADER_FMT.pack(MESSAGE, fl, stream_id, n)] + frame_segs)
            self._count_frames(1)

    def send_many(self, frames: Sequence[Tuple[int, int, int, "bytes | Sequence"]]
                  ) -> None:
        """Write several logical frames in ONE endpoint write (one transport
        notify/wakeup instead of one per frame — the unary fast path sends
        HEADERS+MESSAGE / MESSAGE+TRAILERS fused). Frames whose payload
        exceeds MAX_FRAME_PAYLOAD fall back to the fragmenting path in order.
        On a ``coalesce=True`` writer, non-fragmented calls additionally
        combine ACROSS threads (see the class docstring).
        """
        rdv = self.rdv
        if rdv is not None:
            for ftype, flags, _sid, payload in frames:
                if ftype != MESSAGE or flags & (FLAG_NO_MESSAGE | FLAG_MORE):
                    continue
                n = (sum(len(s) for s in payload)
                     if isinstance(payload, (list, tuple)) else len(payload))
                if rdv.eligible(n, flags_compressed=bool(
                        flags & FLAG_COMPRESSED)):
                    # a rendezvous-bound payload in the batch: degrade to
                    # ordered per-frame sends — the bulk member routes via
                    # the one-sided plane, the rest frame normally, and
                    # per-stream order is preserved because the COMPLETE
                    # control frame is itself sent in sequence
                    for f in frames:
                        self.send(*f)
                    return
        # Encode first: oversized-control-frame failures must surface
        # before any byte is written or queued (an aborted half-written
        # batch would corrupt the coalescing queue's FIFO contract).
        encoded: List[Tuple[int, int, int, List[memoryview], int]] = []
        fragment = False
        for ftype, flags, stream_id, payload in frames:
            segs = ([memoryview(s).cast("B") for s in payload]
                    if isinstance(payload, (list, tuple)) else
                    [memoryview(payload).cast("B")])
            segs = [s for s in segs if len(s)]
            total = sum(len(s) for s in segs)
            if ftype == MESSAGE and flags & FLAG_COMPRESSED:
                segs, total, did = _compress_segs(segs, total)
                if not did:  # incompressible: send as-is, clear the bit
                    flags &= ~FLAG_COMPRESSED
            if total > MAX_FRAME_PAYLOAD:
                if ftype != MESSAGE:
                    raise FrameError(
                        f"control frame payload {total} exceeds "
                        f"{MAX_FRAME_PAYLOAD}; metadata too large")
                fragment = True
            encoded.append((ftype, flags, stream_id, segs, total))
        if fragment:
            # Fragmenting calls stay on the direct path whole (their
            # per-stream order must not straddle the pending queue).
            self._flush_thread_batch()
            batch: List[memoryview] = []
            nframes = 0
            for ftype, flags, stream_id, segs, total in encoded:
                if total > MAX_FRAME_PAYLOAD:
                    if batch:
                        with self._lock:
                            self._ep.write(batch)
                        self._count_frames(nframes)
                        batch, nframes = [], 0
                    self._send_fragmented(flags, stream_id, segs, total)
                    continue
                batch.append(memoryview(
                    HEADER_FMT.pack(ftype, flags, stream_id, total)))
                batch.extend(segs)
                nframes += 1
            if batch:
                with self._lock:
                    self._ep.write(batch)
                self._count_frames(nframes)
            return
        tb = getattr(self._tls, "batch", None)
        batch = tb[1] if tb is not None else []
        nbytes = 0
        for ftype, flags, stream_id, segs, total in encoded:
            batch.append(memoryview(
                HEADER_FMT.pack(ftype, flags, stream_id, total)))
            batch.extend(segs)
            nbytes += HEADER_FMT.size + total
        if tb is not None:  # thread batch: flushed at context exit
            tb[0] += len(encoded)
            self._count_frames(len(encoded))
            return
        if not batch:
            return
        if not self._coalesce:
            with self._lock:
                self._ep.write(batch)
            self._count_frames(len(encoded))
            return
        # counted at queue time: the frames are committed (in order) even
        # though the flusher writes them — a ring record posted after this
        # call must gate on them
        self._count_frames(len(encoded))
        with self._pend_lock:
            self._pending.append((nbytes, batch))
            if self._flushing:
                return  # the in-flight flusher writes it: zero extra wakeups
            self._flushing = True
        self._flush_pending()

    def _count_frames(self, n: int) -> None:
        if not n:
            return
        with self._fs_lock:
            self.frames_sent += n

    # -- per-thread frame batching (tpurpc-pulse, ISSUE 13) -------------------

    def batch(self):
        """Context manager: non-fragmenting frames written by THIS thread
        inside the block queue and flush as ONE gathered writev at exit —
        a burst of small control RPCs (e.g. a migration drain's N sequence
        handoffs) costs one transport write instead of N.  Oversized/
        fragmenting frames flush the queue first, preserving order; other
        threads' writes are untouched (their order against the batch is
        already unconstrained)."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            prev = getattr(self._tls, "batch", None)
            self._tls.batch = [0, []]  # [n_frames, gather segs]
            try:
                yield
            finally:
                tb, self._tls.batch = self._tls.batch, prev
                if tb[1]:
                    with self._lock:
                        self._ep.write(tb[1])
                    from tpurpc.utils import stats as _stats

                    _stats.batch_hist("ctrl_call_batch").record(
                        max(1, tb[0]))
        return _cm()

    def _flush_thread_batch(self) -> None:
        tb = getattr(self._tls, "batch", None)
        if tb is not None and tb[1]:
            segs = tb[1]
            tb[0], tb[1] = 0, []
            with self._lock:
                self._ep.write(segs)

    def _flush_pending(self) -> None:
        """Drain the coalescing queue, one capped gathered writev at a
        time, until it is empty (then hand back the flusher role). A write
        failure drops the queue — the connection is dying and every server
        response path treats sends as best-effort."""
        from tpurpc.utils import stats as _stats

        while True:
            with self._pend_lock:
                if not self._pending:
                    self._flushing = False
                    return
                take: List[memoryview] = []
                nresp = size = 0
                while self._pending and (
                        not take
                        or size + self._pending[0][0] <= self._max_coalesce):
                    nb, segs = self._pending.pop(0)
                    take.extend(segs)
                    size += nb
                    nresp += 1
            try:
                with self._lock:
                    self._ep.write(take)
            except BaseException:
                with self._pend_lock:
                    self._pending.clear()
                    self._flushing = False
                raise
            _stats.batch_hist("resp_coalesce").record(nresp)

    def send_preface(self) -> None:
        with self._lock:
            self._ep.write(MAGIC)


#: Returned by read_frame when a MESSAGE frame was routed to the sink — the
#: caller's loop just continues; there is no Frame object for bulk payloads.
CONSUMED = object()


class Assembly:
    """Per-stream receive buffer with a WRITABLE TAIL: ring/socket drains land
    directly in message storage, removing the scratch-bounce pass (profiled:
    one full extra memory pass per payload byte on the 4 MiB streaming path).

    Backing store is uninitialized numpy memory grown by 2× (each MESSAGE
    frame reserves its announced length up front, so relocations are
    amortized and over-allocation is bounded at 2× the message — consumers
    alias ``take()``'s view, pinning the whole backing array, so waste is
    resident waste). ``take()`` detaches the filled prefix — consumers may
    alias it indefinitely (the tensor codec's zero-copy decode does), so the
    next message gets fresh storage instead of a reuse-after-free."""

    __slots__ = ("_buf", "_used", "oversized")

    def __init__(self):
        self._buf = None
        self._used = 0
        #: the in-flight message tripped the receive-size limit: remaining
        #: fragments are consumed-and-discarded (framing stays in sync) and
        #: the sink's commit delivers RESOURCE_EXHAUSTED instead of a message
        self.oversized = False

    def __len__(self) -> int:
        return self._used

    def reserve(self, n: int) -> None:
        """Ensure ``n`` more bytes are writable after the filled prefix."""
        import numpy as np

        need = self._used + n
        cap = 0 if self._buf is None else self._buf.nbytes
        if need <= cap:
            return
        new = np.empty(max(need, cap * 2, 4096), np.uint8)
        if self._used:
            new[:self._used] = self._buf[:self._used]
            _ledger.host_copy(self._used)  # relocation is a real copy
        self._buf = new

    def tail(self, n: int) -> memoryview:
        """Writable view of the next ``n`` reserved bytes."""
        return memoryview(self._buf.data)[self._used:self._used + n]

    def advance(self, n: int) -> None:
        self._used += n

    def append(self, data) -> None:
        n = len(data)
        if n:
            self.reserve(n)
            self.tail(n)[:] = data
            self._used += n

    def take(self):
        """Detach and return the filled prefix (memoryview over the storage);
        the assembly resets to empty with fresh backing and a clear
        :attr:`oversized` flag."""
        self.oversized = False
        if self._buf is None:
            return memoryview(b"")
        out = memoryview(self._buf.data)[:self._used]
        self._buf = None
        self._used = 0
        return out


class MessageSink:
    """Destination for MESSAGE payload bytes, bypassing Frame materialization.

    The reader drains each fragment's bytes straight into the per-stream
    :class:`Assembly` (one copy off the wire: transport → message storage —
    the receive-side half of the copy ledger the north star optimizes)."""

    #: Largest acceptable assembled message; None = unlimited. Enforced by
    #: the FrameReader BEFORE buffering (an over-limit message is discarded
    #: in transit, never held in memory) — grpc.max_receive_message_length /
    #: resource_quota.cc's receive-side role.
    max_message_bytes = None

    def buffer_for(self, stream_id: int) -> Assembly:
        raise NotImplementedError

    def commit(self, stream_id: int, flags: int) -> None:
        raise NotImplementedError


class FrameReader:
    """Buffered frame parser over the endpoint's read()/read_into() stream."""

    def __init__(self, endpoint: Endpoint, expect_preface: bool = False):
        self._ep = endpoint
        self._buf = bytearray()
        self._eof = False
        self._need_preface = expect_preface
        self._scratch = bytearray(MAX_FRAME_PAYLOAD)
        self._scratch_mv = memoryview(self._scratch)
        self.sink: Optional[MessageSink] = None
        #: tpurpc-pulse: called right before each sink commit.  The
        #: descriptor-ring consumer hangs its drain here so a control op
        #: posted BEFORE this frame was sent (visible in shm by store
        #: order) delivers first — per-stream order survives the split
        #: control plane even for sink-routed MESSAGEs.
        self.pre_commit = None
        # In-flight sink-routed MESSAGE interrupted by ReadTimeout:
        # (dst, rest, stream_id, flags) — resumed by the next read_frame.
        self._pending_msg: Optional[tuple] = None

    #: Opportunistic read-ahead for control structures. One endpoint read
    #: (syscall / ring drain) usually picks up a whole burst of small frames
    #: — header+metadata+message+trailers of the unary fast path — instead of
    #: one read per deficit (profiled: ~10 ring drains per 64B RPC before).
    #: The cost is bounded: at most this many MESSAGE-payload bytes get
    #: dragged through _buf (then handed to the sink from there), noise next
    #: to a saved syscall on the small path and next to the payload itself on
    #: the bulk path (8 KiB per ≥1 MiB frame ≤ 0.8%).
    READ_AHEAD = 8192

    def _fill(self, need: int, timeout: Optional[float] = None) -> bool:
        """Grow the buffer to ≥ need bytes; False on clean EOF first."""
        while len(self._buf) < need:
            if self._eof:
                return False
            want = max(need - len(self._buf), self.READ_AHEAD)
            n = self._ep.read_into(self._scratch_mv[:want], timeout=timeout)
            if n == 0:
                self._eof = True
                return len(self._buf) >= need
            self._buf += self._scratch_mv[:n]
        return True

    def _drain_message(self, dst: Assembly, rest: int, stream_id: int,
                       flags: int, timeout: Optional[float]):
        """Stream the remaining payload straight into the assembly buffer —
        the transport writes message storage directly (no scratch bounce).

        A ReadTimeout mid-payload parks the progress in ``_pending_msg`` so the
        next read_frame resumes exactly where the wire stopped — the framing
        never desyncs."""
        try:
            while rest:
                if dst.oversized:
                    # consume-and-discard through the scratch: the framing
                    # must stay in sync even for rejected messages
                    n = self._ep.read_into(
                        self._scratch_mv[:min(rest, MAX_FRAME_PAYLOAD)],
                        timeout=timeout)
                else:
                    n = self._ep.read_into(dst.tail(rest), timeout=timeout)
                if n == 0:
                    self._eof = True
                    raise FrameError("truncated frame payload at EOF")
                if not dst.oversized:
                    dst.advance(n)
                    _ledger.host_copy(n)
                rest -= n
        except TimeoutError:
            self._pending_msg = (dst, rest, stream_id, flags)
            raise
        self._pending_msg = None
        if self.pre_commit is not None:
            self.pre_commit()
        self.sink.commit(stream_id, flags)
        return CONSUMED

    def read_frame(self, timeout: Optional[float] = None):
        """Next control Frame, CONSUMED for sink-routed MESSAGE frames, or
        None at clean EOF.  Raises EndpointError/FrameError."""
        if self._pending_msg is not None:
            dst, rest, stream_id, flags = self._pending_msg
            return self._drain_message(dst, rest, stream_id, flags, timeout)
        if self._need_preface:
            if not self._fill(len(MAGIC), timeout):
                return None
            if bytes(self._buf[:len(MAGIC)]) != MAGIC:
                raise FrameError(f"bad connection preface: {bytes(self._buf[:8])!r}")
            del self._buf[:len(MAGIC)]
            self._need_preface = False
        if not self._fill(HEADER_FMT.size, timeout):
            if self._buf:
                raise FrameError("truncated frame header at EOF")
            return None
        ftype, flags, stream_id, length = HEADER_FMT.unpack_from(self._buf)
        if length > MAX_FRAME_PAYLOAD:
            raise FrameError(f"frame length {length} exceeds max {MAX_FRAME_PAYLOAD}")
        hdr = HEADER_FMT.size
        if ftype == MESSAGE and self.sink is not None:
            dst = self.sink.buffer_for(stream_id)
            limit = self.sink.max_message_bytes
            if (limit is not None and not dst.oversized
                    and len(dst) + length > limit):
                dst.take()  # free what was buffered; the message is doomed
                dst.oversized = True  # AFTER take() (take clears the flag)
            have = min(length, len(self._buf) - hdr)
            if dst.oversized:
                del self._buf[:hdr + have]
                return self._drain_message(dst, length - have, stream_id,
                                           flags, timeout)
            dst.reserve(length)  # announced frame length: presize ONCE
            if have:
                dst.append(memoryview(self._buf)[hdr:hdr + have])
                _ledger.host_copy(have)
            del self._buf[:hdr + have]
            return self._drain_message(dst, length - have, stream_id, flags,
                                       timeout)
        if not self._fill(hdr + length, timeout):
            raise FrameError("truncated frame payload at EOF")
        payload = bytes(self._buf[hdr:hdr + length])
        del self._buf[:hdr + length]
        return Frame(ftype, flags, stream_id, payload)
