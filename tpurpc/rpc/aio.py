"""asyncio surface: async servers and channels over the threaded core.

The ``grpc.aio`` analog (reference: ``src/python/grpcio/grpc/aio/``,
SURVEY §2.4) — async handlers and awaitable calls so a TPU serving process
overlaps host IO with device compute: while one handler awaits a device
result (or a downstream RPC), every other handler keeps running on the same
event loop.

Design position: grpc.aio re-implements its whole transport on asyncio;
tpurpc BRIDGES instead. The threaded data plane (endpoint readers, frame
writers, ring pollers) is unchanged — it is where the zero-copy and
wakeup machinery lives — and the asyncio layer adapts at the call boundary:

* server: async behaviors are scheduled onto the server's event loop via
  ``run_coroutine_threadsafe``; the dispatching pool worker parks on the
  future while EVERY async handler interleaves on the loop. Concurrency is
  bounded by ``max_workers`` exactly as in the sync server; the win is that
  handlers themselves are coroutines (await device work, fan out calls)
  rather than thread-per-await.
* client: awaitable multicallables run the blocking call machinery in the
  loop's default executor; streaming responses arrive as async iterators.

Four call shapes on both sides, secure ports/channels included.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, AsyncIterator, Callable, Iterator, Optional

import importlib

# NOT `from tpurpc.rpc import server`: the package exports grpcio-shaped
# server()/insecure_channel() FUNCTIONS that shadow the submodules.
_server_mod = importlib.import_module("tpurpc.rpc.server")
_channel_mod = importlib.import_module("tpurpc.rpc.channel")
from tpurpc.rpc.status import Deserializer, Metadata, Serializer
from tpurpc.rpc.status import identity_codec as _identity

__all__ = ["Server", "Channel", "server", "insecure_channel",
           "secure_channel", "unary_unary_rpc_method_handler",
           "unary_stream_rpc_method_handler",
           "stream_unary_rpc_method_handler",
           "stream_stream_rpc_method_handler"]


# ---------------------------------------------------------------------------
# Handler factories: same taxonomy, async behaviors.
# ---------------------------------------------------------------------------

class _AioHandler:
    """Marker wrapper: an async behavior + codecs, adapted at registration."""

    __slots__ = ("kind", "behavior", "request_deserializer",
                 "response_serializer")

    def __init__(self, kind: str, behavior: Callable,
                 request_deserializer: Deserializer = _identity,
                 response_serializer: Serializer = _identity):
        self.kind = kind
        self.behavior = behavior
        self.request_deserializer = request_deserializer
        self.response_serializer = response_serializer


def unary_unary_rpc_method_handler(behavior, request_deserializer=_identity,
                                   response_serializer=_identity):
    return _AioHandler("unary_unary", behavior, request_deserializer,
                       response_serializer)


def unary_stream_rpc_method_handler(behavior, request_deserializer=_identity,
                                    response_serializer=_identity):
    return _AioHandler("unary_stream", behavior, request_deserializer,
                       response_serializer)


def stream_unary_rpc_method_handler(behavior, request_deserializer=_identity,
                                    response_serializer=_identity):
    return _AioHandler("stream_unary", behavior, request_deserializer,
                       response_serializer)


def stream_stream_rpc_method_handler(behavior, request_deserializer=_identity,
                                     response_serializer=_identity):
    return _AioHandler("stream_stream", behavior, request_deserializer,
                       response_serializer)


class _LoopRef:
    """The server's event loop, captured at ``await server.start()``; sync
    adapters read it at call time (registration happens before start)."""

    __slots__ = ("loop",)

    def __init__(self):
        self.loop: Optional[asyncio.AbstractEventLoop] = None


# ---------------------------------------------------------------------------
# Blocking↔async bridging primitives.
#
# NEVER the loop's default executor for indefinite waits: it is a small
# shared pool (min(32, cpu+4) threads), and N concurrent streams parking
# blocking reads there deadlock the whole loop once N exceeds it (reviewer
# finding). Every indefinitely-blocking wait below gets a DEDICATED daemon
# thread, and every cross-thread future wait is guarded against the loop
# stopping underneath it.
# ---------------------------------------------------------------------------

class _Raise:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def _guarded_result(fut, loop, what: str):
    """``concurrent.futures.Future.result()`` that cannot outlive the loop:
    polls in short slices and bails (cancelling the work) if the loop closed
    — otherwise a stopped ``asyncio.run()`` strands the waiting thread
    forever."""
    import concurrent.futures as cf

    while True:
        try:
            return fut.result(timeout=0.5)
        except cf.TimeoutError:
            if loop.is_closed() or not loop.is_running():
                fut.cancel()
                raise RuntimeError(f"event loop stopped while awaiting {what}")


async def _call_in_thread(fn):
    """Run a blocking callable on its own daemon thread; await the outcome."""
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()

    def _deliver(setter, value):
        if not fut.cancelled():
            setter(value)

    def work():
        try:
            res = fn()
        except BaseException as exc:
            try:
                loop.call_soon_threadsafe(_deliver, fut.set_exception, exc)
            except RuntimeError:
                pass  # loop closed: nobody is waiting anymore
        else:
            try:
                loop.call_soon_threadsafe(_deliver, fut.set_result, res)
            except RuntimeError:
                pass

    threading.Thread(target=work, daemon=True, name="tpurpc-aio-call").start()
    return await fut


def _sync_to_async_iter(make_iter: Callable[[], Any]) -> AsyncIterator:
    """Blocking iterable → async iterator via ONE dedicated pump thread.

    The pump owns the sync iterator's frame, so abandonment cleanup is safe
    and complete: when the async consumer drops the generator, the pump
    cancels the underlying Call (if the source has ``cancel``) and closes
    the iterator, releasing transport credits instead of leaking a parked
    thread. Bounded queue = backpressure toward the producer."""
    _DONE = object()

    async def gen():
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue(8)
        state = {"dropped": False}

        def put_item(item) -> bool:
            import concurrent.futures as cf

            fut = asyncio.run_coroutine_threadsafe(q.put(item), loop)
            while True:
                try:
                    fut.result(timeout=0.5)
                    return True
                except cf.TimeoutError:
                    if (state["dropped"] or loop.is_closed()
                            or not loop.is_running()):
                        fut.cancel()
                        return False

        def pump():
            src = None
            it = None
            try:
                src = make_iter()  # may block (opens the call)
                it = iter(src)
                for item in it:
                    if state["dropped"] or not put_item(item):
                        break
                else:
                    put_item(_DONE)
                    return
            except BaseException as exc:  # delivered to the consumer
                put_item(_Raise(exc))
                return
            # abandoned mid-stream: free the server + transport credits
            for obj, meth in ((src, "cancel"), (it, "close")):
                fn = getattr(obj, meth, None)
                if fn is not None:
                    try:
                        fn()
                    except Exception:
                        pass

        threading.Thread(target=pump, daemon=True,
                         name="tpurpc-aio-pump").start()
        try:
            while True:
                item = await q.get()
                if item is _DONE:
                    return
                if isinstance(item, _Raise):
                    raise item.exc
                yield item
        finally:
            state["dropped"] = True
            while not q.empty():  # unblock a pump parked on a full queue
                q.get_nowait()

    return gen()


def _aiter_requests(sync_iter: Iterator, loop) -> AsyncIterator:
    """Thread-fed sync request iterator → async iterator for the handler.
    (loop is implicit in the returned generator; parameter kept for the
    server adapters' call shape.)"""
    return _sync_to_async_iter(lambda: sync_iter)


def _adapt(handler: _AioHandler, loop_ref: _LoopRef):
    """Async behavior → sync RpcMethodHandler the threaded server can run.

    The pool worker parks on ``Future.result()`` while the coroutine runs on
    the loop; async-generator responses are pulled one item per
    ``run_coroutine_threadsafe`` so the worker writes each response with the
    existing (blocking, flow-controlled) writer."""
    ab = handler.behavior

    def _loop() -> asyncio.AbstractEventLoop:
        loop = loop_ref.loop
        if loop is None:
            raise RuntimeError("aio.Server not started")
        return loop

    def _pump_agen(agen, loop):
        """Drive an async generator from the worker thread, one item per
        loop round-trip; on EARLY CLOSE (client cancel/disconnect throws
        GeneratorExit at our yield) aclose() the agen ON THE LOOP so the
        handler's finally/async-with cleanup actually runs — a GC'd
        un-aclosed asyncgen from a non-loop thread silently never runs it."""
        try:
            while True:
                try:
                    yield _guarded_result(
                        asyncio.run_coroutine_threadsafe(
                            agen.__anext__(), loop),
                        loop, "handler response")
                except StopAsyncIteration:
                    return
        finally:
            try:
                _guarded_result(
                    asyncio.run_coroutine_threadsafe(agen.aclose(), loop),
                    loop, "handler aclose")
            except Exception:
                pass

    if handler.kind == "unary_unary":
        def behavior(req, ctx):
            loop = _loop()
            return _guarded_result(
                asyncio.run_coroutine_threadsafe(ab(req, ctx), loop),
                loop, "handler result")
        factory = _server_mod.unary_unary_rpc_method_handler
    elif handler.kind == "unary_stream":
        def behavior(req, ctx):
            loop = _loop()
            yield from _pump_agen(ab(req, ctx), loop)
        factory = _server_mod.unary_stream_rpc_method_handler
    elif handler.kind == "stream_unary":
        def behavior(req_iter, ctx):
            loop = _loop()
            return _guarded_result(
                asyncio.run_coroutine_threadsafe(
                    ab(_aiter_requests(req_iter, loop), ctx), loop),
                loop, "handler result")
        factory = _server_mod.stream_unary_rpc_method_handler
    elif handler.kind == "stream_stream":
        def behavior(req_iter, ctx):
            loop = _loop()
            yield from _pump_agen(ab(_aiter_requests(req_iter, loop), ctx),
                                  loop)
        factory = _server_mod.stream_stream_rpc_method_handler
    else:
        raise ValueError(f"bad handler kind {handler.kind}")
    return factory(behavior, handler.request_deserializer,
                   handler.response_serializer)


class Server:
    """grpc.aio-shaped server: async handlers over the threaded transport."""

    def __init__(self, max_workers: int = 32,
                 max_receive_message_length: Optional[int] = None):
        self._sync = _server_mod.Server(
            max_workers=max_workers,
            max_receive_message_length=max_receive_message_length)
        self._loop_ref = _LoopRef()

    # registration (sync, like grpc.aio) -------------------------------------

    def add_method(self, path: str, handler) -> None:
        if isinstance(handler, _AioHandler):
            handler = _adapt(handler, self._loop_ref)
        self._sync.add_method(path, handler)

    def add_service(self, service: str, method_handlers) -> None:
        for name, h in dict(method_handlers).items():
            self.add_method(f"/{service}/{name}", h)

    # grpcio-generated-code surface (sync-behavior handlers pass straight
    # through to the threaded server's adaptation; see rpc/server.py)
    def add_generic_rpc_handlers(self, generic_handlers) -> None:
        self._sync.add_generic_rpc_handlers(generic_handlers)

    def add_registered_method_handlers(self, service, method_handlers) -> None:
        self._sync.add_registered_method_handlers(service, method_handlers)

    def add_insecure_port(self, address: str) -> int:
        return self._sync.add_insecure_port(address)

    def add_secure_port(self, address: str, server_credentials) -> int:
        return self._sync.add_secure_port(address, server_credentials)

    # lifecycle (async, like grpc.aio) ----------------------------------------

    async def start(self) -> None:
        self._loop_ref.loop = asyncio.get_running_loop()
        self._sync.start()

    async def stop(self, grace: Optional[float] = None) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, lambda: self._sync.stop(grace=grace or 0))

    async def wait_for_termination(self,
                                   timeout: Optional[float] = None) -> bool:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: self._sync.wait_for_termination(timeout=timeout))


def server(migration_thread_pool=None, handlers=None, interceptors=None,
           options=None, maximum_concurrent_rpcs=None, compression=None, *,
           max_workers: int = 32, **kw) -> Server:
    """grpc.aio.server-shaped: the stock call (executor first, options
    list, advisory kwargs) runs verbatim — same mapping as the sync
    :func:`tpurpc.rpc.server.server`."""
    if isinstance(migration_thread_pool, int):  # legacy server(N)
        max_workers = migration_thread_pool
    elif migration_thread_pool is not None:
        workers = getattr(migration_thread_pool, "_max_workers", None)
        if workers:
            max_workers = workers
    if options:
        kw.setdefault("max_receive_message_length",
                      dict(options).get("grpc.max_receive_message_length"))
    srv = Server(max_workers=max_workers, **kw)
    if handlers:
        for gh in handlers:
            srv.add_generic_rpc_handlers((gh,))
    return srv


# ---------------------------------------------------------------------------
# Client.
# ---------------------------------------------------------------------------

class _SyncedAsyncIterator:
    """Feed a SYNC request iterator (consumed by the blocking call machinery
    in a worker thread) from an ASYNC source running on the caller's loop."""

    def __init__(self, async_iterable, loop: asyncio.AbstractEventLoop):
        self._ait = async_iterable.__aiter__()
        self._loop = loop

    def __iter__(self):
        return self

    def __next__(self):
        fut = asyncio.run_coroutine_threadsafe(self._ait.__anext__(),
                                               self._loop)
        try:
            return _guarded_result(fut, self._loop, "request item")
        except StopAsyncIteration:
            raise StopIteration from None
        except RuntimeError:
            # loop stopped (deadline fired, asyncio.run returned): end the
            # stream instead of stranding the sender thread forever
            raise StopIteration from None


class Channel:
    """grpc.aio-shaped channel: awaitable calls over the threaded client."""

    def __init__(self, target: str, *, credentials=None, **kw):
        self._sync = _channel_mod.Channel(target, credentials=credentials,
                                          **kw)

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def close(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._sync.close)

    async def ping(self, timeout: float = 5.0) -> float:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: self._sync.ping(timeout))

    def unary_unary(self, method: str, request_serializer=_identity,
                    response_deserializer=_identity, **grpcio_kwargs):
        mc = self._sync.unary_unary(method, request_serializer,
                                    response_deserializer, **grpcio_kwargs)

        async def call(request, timeout: Optional[float] = None,
                       metadata: Optional[Metadata] = None):
            return await _call_in_thread(
                lambda: mc(request, timeout=timeout, metadata=metadata))

        return call

    def unary_stream(self, method: str, request_serializer=_identity,
                     response_deserializer=_identity, **grpcio_kwargs):
        mc = self._sync.unary_stream(method, request_serializer,
                                     response_deserializer, **grpcio_kwargs)

        def call(request, timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None) -> AsyncIterator:
            return _sync_to_async_iter(
                lambda: mc(request, timeout=timeout, metadata=metadata))

        return call

    def stream_unary(self, method: str, request_serializer=_identity,
                     response_deserializer=_identity, **grpcio_kwargs):
        mc = self._sync.stream_unary(method, request_serializer,
                                     response_deserializer, **grpcio_kwargs)

        async def call(request_iterator, timeout: Optional[float] = None,
                       metadata: Optional[Metadata] = None):
            loop = asyncio.get_running_loop()
            if hasattr(request_iterator, "__aiter__"):
                request_iterator = _SyncedAsyncIterator(request_iterator,
                                                        loop)
            return await _call_in_thread(
                lambda: mc(request_iterator, timeout=timeout,
                           metadata=metadata))

        return call

    def stream_stream(self, method: str, request_serializer=_identity,
                      response_deserializer=_identity, **grpcio_kwargs):
        mc = self._sync.stream_stream(method, request_serializer,
                                      response_deserializer, **grpcio_kwargs)

        def call(request_iterator, timeout: Optional[float] = None,
                 metadata: Optional[Metadata] = None) -> AsyncIterator:
            async def gen():
                loop = asyncio.get_running_loop()
                reqs = request_iterator
                if hasattr(reqs, "__aiter__"):
                    reqs = _SyncedAsyncIterator(reqs, loop)
                async for item in _sync_to_async_iter(
                        lambda: mc(reqs, timeout=timeout,
                                   metadata=metadata)):
                    yield item

            return gen()

        return call


def insecure_channel(target: str, **kw) -> Channel:
    return Channel(target, **kw)


def secure_channel(target: str, credentials, **kw) -> Channel:
    return Channel(target, credentials=credentials, **kw)


class NativeChannel:
    """grpc.aio-shaped wrapper over :class:`tpurpc.rpc.native_client.
    NativeChannel`: unary calls submit through the channel's completion
    queue and await the completion — N coroutines = N calls in flight on
    one connection with ONE puller thread, no executor thread per call
    (the async face of the ctypes fast path; GRPC_PLATFORM_TYPE is
    honored inside the .so). The executor is used only for close/ping
    and for calls with a non-identity serializer (serialization stays
    off the event loop)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        from tpurpc.rpc.native_client import NativeChannel as _Sync

        self._sync = _Sync(host, port, connect_timeout)

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def close(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._sync.close)

    async def ping(self, timeout: float = 5.0) -> float:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: self._sync.ping(timeout))

    def unary_unary(self, method: str, request_serializer=_identity,
                    response_deserializer=_identity):
        # Raw-bytes multicallable: the response deserializer must NOT run
        # on the channel's single CQ puller thread (it would serialize all
        # in-flight completions behind each decode) — it runs per-call
        # below, off-loop when non-trivial.
        mc = self._sync.unary_unary(method, request_serializer, None)

        async def call(request, timeout=None):
            # Submit through the channel's completion queue and await the
            # wrapped Future: N coroutines = N calls in flight on ONE
            # connection with one puller thread — no executor thread per
            # call. Heavy codecs run on the executor so neither the event
            # loop (serializer) nor the puller (deserializer) stalls;
            # bare-bytes calls never touch the executor at all.
            loop = asyncio.get_running_loop()
            if request_serializer is _identity:
                fut = mc.future(request, timeout=timeout)
            else:
                fut = await loop.run_in_executor(
                    None, lambda: mc.future(request, timeout=timeout))
            body = await asyncio.wrap_future(fut)
            if response_deserializer is _identity:
                return body
            return await loop.run_in_executor(
                None, response_deserializer, body)

        return call
