"""gRPC Health Checking service (grpc.health.v1) for tpurpc servers.

The standard ``/grpc.health.v1.Health/{Check,Watch}`` protocol every gRPC
deployment's load balancers and orchestrators probe (the reference inherits
it from upstream: ``src/proto/grpc/health/v1/health.proto`` +
``src/python/grpcio_health_checking``). Message encoding is hand-rolled —
the messages are one field each, and hard-coding the two tag bytes beats a
protobuf dependency:

    HealthCheckRequest  { string service = 1; }          → 0x0A len bytes
    HealthCheckResponse { ServingStatus status = 1; }     → 0x08 varint

Wire-compatible with stock grpcio health clients over the h2 path (tested),
and with tpurpc-native channels over every transport.
"""

from __future__ import annotations

import enum
import threading
from typing import Dict, Iterator

from tpurpc.rpc.server import (Server, unary_stream_rpc_method_handler,
                               unary_unary_rpc_method_handler)
from tpurpc.rpc.status import AbortError, StatusCode

SERVICE_NAME = "grpc.health.v1.Health"
#: the conventional key for "the server as a whole"
OVERALL = ""


class ServingStatus(enum.IntEnum):
    UNKNOWN = 0
    SERVING = 1
    NOT_SERVING = 2
    SERVICE_UNKNOWN = 3  # Watch-only, per the health spec


from tpurpc.wire.protowire import decode_varint as _decode_varint
from tpurpc.wire.protowire import encode_varint as _encode_varint


def encode_request(service: str) -> bytes:
    raw = service.encode("utf-8")
    if not raw:
        return b""  # proto3: default value omitted
    return b"\x0a" + _encode_varint(len(raw)) + raw


def decode_request(buf) -> str:
    data = bytes(buf)
    pos = 0
    service = ""
    while pos < len(data):
        tag = data[pos]
        pos += 1
        if tag == 0x0A:  # field 1, length-delimited
            ln, pos = _decode_varint(data, pos)
            service = data[pos:pos + ln].decode("utf-8")
            pos += ln
        elif tag & 0x07 == 0:  # unknown varint field
            _, pos = _decode_varint(data, pos)
        elif tag & 0x07 == 2:  # unknown length-delimited field
            ln, pos = _decode_varint(data, pos)
            pos += ln
        else:
            break  # unknown fixed-width field: nothing legal follows here
    return service


def encode_response(status: ServingStatus) -> bytes:
    if status == ServingStatus.UNKNOWN:
        return b""
    return b"\x08" + _encode_varint(int(status))


def decode_response(buf) -> ServingStatus:
    data = bytes(buf)
    pos = 0
    while pos < len(data):
        tag = data[pos]
        pos += 1
        if tag == 0x08:
            val, pos = _decode_varint(data, pos)
            return ServingStatus(val)
        elif tag & 0x07 == 0:
            _, pos = _decode_varint(data, pos)
        elif tag & 0x07 == 2:
            ln, pos = _decode_varint(data, pos)
            pos += ln
        else:
            break
    return ServingStatus.UNKNOWN


class HealthServicer:
    """Status registry + the two health RPCs (grpcio's HealthServicer shape).

    ``set(service, status)`` updates a service's state and wakes every
    watcher; the overall server state lives under the empty service name.
    """

    def __init__(self):
        self._lock = threading.Condition()
        self._statuses: Dict[str, ServingStatus] = {
            OVERALL: ServingStatus.SERVING}
        self._epoch = 0  # bumped per set(); watchers wait on it

    def set(self, service: str, status: ServingStatus) -> None:
        with self._lock:
            self._statuses[service] = ServingStatus(status)
            self._epoch += 1
            self._lock.notify_all()

    def set_all(self, status: ServingStatus) -> None:
        """Flip EVERY registered service (the overall key included) in one
        epoch — what :meth:`tpurpc.rpc.server.Server.drain` calls so LBs
        and watchers see the whole backend leave rotation at once
        (grpcio's ``enter_graceful_shutdown`` analog)."""
        with self._lock:
            for service in self._statuses:
                self._statuses[service] = ServingStatus(status)
            self._epoch += 1
            self._lock.notify_all()

    def _check(self, raw, ctx) -> bytes:
        try:
            service = decode_request(raw)
        except (ValueError, IndexError, UnicodeDecodeError):
            raise AbortError(StatusCode.INVALID_ARGUMENT,
                            "malformed HealthCheckRequest") from None
        with self._lock:
            status = self._statuses.get(service)
        if status is None:
            # spec: Check on an unregistered service → NOT_FOUND
            raise AbortError(StatusCode.NOT_FOUND,
                             f"unknown service {service!r}")
        return encode_response(status)

    def _watch(self, raw, ctx) -> Iterator[bytes]:
        try:
            service = decode_request(raw)
        except (ValueError, IndexError, UnicodeDecodeError):
            raise AbortError(StatusCode.INVALID_ARGUMENT,
                            "malformed HealthCheckRequest") from None
        last = None
        while ctx.is_active():
            with self._lock:
                status = self._statuses.get(service,
                                            ServingStatus.SERVICE_UNKNOWN)
                epoch = self._epoch
            if status != last:
                last = status
                yield encode_response(status)
            with self._lock:
                # wake on any set(); re-check OUR service + ctx liveness.
                # Bounded wait so a cancelled stream is noticed promptly.
                if self._epoch == epoch:
                    self._lock.wait(timeout=0.25)

    def add_to_server(self, server: Server) -> None:
        server.add_method(
            f"/{SERVICE_NAME}/Check",
            unary_unary_rpc_method_handler(self._check))
        server.add_method(
            f"/{SERVICE_NAME}/Watch",
            unary_stream_rpc_method_handler(self._watch))
        # tpurpc-fleet: the server drives this servicer on drain()
        # (NOT_SERVING fleet-wide while connections bleed)
        server._health_servicer = self


def add_health_servicer(server: Server) -> HealthServicer:
    """Convenience: attach a fresh HealthServicer; returns it for set()."""
    servicer = HealthServicer()
    servicer.add_to_server(server)
    return servicer
