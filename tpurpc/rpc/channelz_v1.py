"""grpc.channelz.v1 wire-compatible service — the standard introspection
protocol (ref: inherited ``src/cpp/server/channelz/``; proto at
``src/proto/grpc/channelz/channelz.proto``). Hand-rolled wire like
health/reflection (:mod:`tpurpc.wire.protowire`), covering the subset
debugging tools actually walk:

    GetServers / GetServer           (ServerRef + ServerData counters +
                                      listen SocketRefs)
    GetTopChannels / GetChannel      (ChannelRef + ChannelData: state,
                                      target, call counters)
    GetServerSockets / GetSocket     (live connections: SocketRef per
                                      connection; SocketData streams_started
                                      + local/remote TcpIpAddress)

Pagination follows the proto contract: requests carry ``start_*_id`` and
``max_results``; responses list id-ordered entities and set ``end`` when
the page reaches the registry's end. The richer tpurpc-native JSON
snapshot stays at ``/tpurpc.Channelz/Get`` (:func:`add_channelz_service`).
"""

from __future__ import annotations

from tpurpc.rpc import channelz as _cz
from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
from tpurpc.rpc.status import AbortError, StatusCode
from tpurpc.wire.protowire import fields, ld, vf

SERVICE = "grpc.channelz.v1.Channelz"

# ChannelConnectivityState.State enum values (channelz.proto:
# UNKNOWN=0, IDLE=1, CONNECTING=2, READY=3, TRANSIENT_FAILURE=4, SHUTDOWN=5)
_STATE_IDLE = 1
_STATE_READY = 3
_STATE_TRANSIENT_FAILURE = 4
_STATE_SHUTDOWN = 5

_MAX_PAGE = 100


def _timestamp(unix_s: float) -> bytes:
    if not unix_s:
        return b""
    sec = int(unix_s)
    nanos = int((unix_s - sec) * 1e9)
    return vf(1, sec) + vf(2, nanos)


def _server_msg(sid: int, srv) -> bytes:
    ref = vf(1, sid) + ld(2, b"tpurpc.Server")
    counters = getattr(srv, "call_counters", None)
    data = b""
    if counters is not None:
        data += vf(2, counters.started) + vf(3, counters.succeeded)
        data += vf(4, counters.failed)
        ts = _timestamp(counters.last_call_started)
        if ts:
            data += ld(5, ts)
    out = ld(1, ref) + ld(2, data)
    for port in getattr(srv, "bound_ports", []):
        # SocketRef{socket_id, name}: ids come from the SAME entity-id
        # space as servers/channels (channelz requires global uniqueness —
        # a raw port number would collide with entity ids)
        out += ld(3, vf(1, _cz.socket_id_for(srv, port))
                  + ld(2, f"listen:{port}".encode()))
    return out


def _channel_state(ch) -> int:
    if ch._is_closed():
        return _STATE_SHUTDOWN
    subs = getattr(ch, "_subchannels", [])
    live = [s._conn for s in subs if s._conn is not None and s._conn.alive]
    return _STATE_READY if live else _STATE_IDLE


def _channel_msg(cid: int, ch) -> bytes:
    ref = vf(1, cid) + ld(2, b"tpurpc.Channel")
    data = ld(1, vf(1, _channel_state(ch)))  # ChannelConnectivityState
    addrs = getattr(ch, "_addrs", None)
    if addrs:
        target = ",".join(f"{h}:{p}" for h, p in addrs)
        data += ld(2, target.encode())
    counters = getattr(ch, "call_counters", None)
    if counters is not None:
        data += vf(4, counters.started) + vf(5, counters.succeeded)
        data += vf(6, counters.failed)
        ts = _timestamp(counters.last_call_started)
        if ts:
            data += ld(7, ts)
    return ld(1, ref) + ld(2, data)


def _page_params(raw: bytes):
    start, max_results = 0, _MAX_PAGE
    try:
        for f, _w, v in fields(bytes(raw)):
            if f == 1:
                start = int(v)
            elif f == 2:
                max_results = max(1, min(int(v), _MAX_PAGE))
    except ValueError:
        raise AbortError(StatusCode.INVALID_ARGUMENT,
                         "malformed channelz request") from None
    return start, max_results


def _id_param(raw: bytes) -> int:
    try:
        for f, _w, v in fields(bytes(raw)):
            if f == 1:
                return int(v)
    except ValueError:
        pass
    raise AbortError(StatusCode.INVALID_ARGUMENT,
                     "malformed channelz request")


def _get_servers(raw, _ctx) -> bytes:
    start, n = _page_params(raw)
    rows = [(i, s) for i, s in _cz.live_servers() if i >= start]
    out = b"".join(ld(1, _server_msg(i, s)) for i, s in rows[:n])
    if len(rows) <= n:
        out += vf(2, 1)  # end = true
    return out


def _get_top_channels(raw, _ctx) -> bytes:
    start, n = _page_params(raw)
    rows = [(i, c) for i, c in _cz.live_channels() if i >= start]
    out = b"".join(ld(1, _channel_msg(i, c)) for i, c in rows[:n])
    if len(rows) <= n:
        out += vf(2, 1)
    return out


def _get_server(raw, _ctx) -> bytes:
    want = _id_param(raw)
    for i, s in _cz.live_servers():
        if i == want:
            return ld(1, _server_msg(i, s))
    raise AbortError(StatusCode.NOT_FOUND, f"no server with id {want}")


def _get_channel(raw, _ctx) -> bytes:
    want = _id_param(raw)
    for i, c in _cz.live_channels():
        if i == want:
            return ld(1, _channel_msg(i, c))
    raise AbortError(StatusCode.NOT_FOUND, f"no channel with id {want}")


def _conn_name(conn) -> str:
    ep = getattr(conn, "endpoint", None)
    peer = getattr(ep, "peer", "?")
    local = getattr(ep, "local_address", "?")
    return f"{peer} -> {local}"


def _tcpip_address(addr_str: str) -> bytes:
    """'ipv4:1.2.3.4:56' → Address{tcpip_address{ip_address, port}}."""
    import socket as _socket

    try:
        body = addr_str.split(":", 1)[1] if ":" in addr_str else addr_str
        host, _, port_s = body.rpartition(":")
        packed = _socket.inet_aton(host)
        return ld(1, ld(1, packed) + vf(2, int(port_s)))
    except (OSError, ValueError, IndexError):
        return b""


def _socket_msg(sid: int, conn) -> bytes:
    ref = vf(1, sid) + ld(2, _conn_name(conn).encode())
    data = vf(1, getattr(conn, "streams_started", 0))
    ep = getattr(conn, "endpoint", None)
    out = ld(1, ref) + ld(2, data)
    local = _tcpip_address(getattr(ep, "local_address", "") or "")
    remote = _tcpip_address(getattr(ep, "peer", "") or "")
    if local:
        out += ld(3, local)
    if remote:
        out += ld(4, remote)
    return out


def _get_server_sockets(raw, _ctx) -> bytes:
    # GetServerSocketsRequest: server_id=1, start_socket_id=2, max_results=3
    want, start, limit = 0, 0, _MAX_PAGE
    try:
        for f, _w, v in fields(bytes(raw)):
            if f == 1:
                want = int(v)
            elif f == 2:
                start = int(v)
            elif f == 3:
                limit = max(1, min(int(v), _MAX_PAGE))
    except ValueError:
        raise AbortError(StatusCode.INVALID_ARGUMENT,
                         "malformed channelz request") from None
    for i, s in _cz.live_servers():
        if i == want:
            rows = sorted(
                (_cz.socket_id_for(conn, 0), conn)
                for conn in list(getattr(s, "_connections", [])))
            rows = [(sid, c) for sid, c in rows if sid >= start]
            out = b"".join(
                ld(1, vf(1, sid) + ld(2, _conn_name(c).encode()))
                for sid, c in rows[:limit])
            if len(rows) <= limit:
                out += vf(2, 1)  # end = true
            return out
    raise AbortError(StatusCode.NOT_FOUND, f"no server with id {want}")


def _listen_socket_msg(sid: int, srv, port: int) -> bytes:
    ref = vf(1, sid) + ld(2, f"listen:{port}".encode())
    return ld(1, ref) + ld(2, b"")  # a listen socket carries no stream data


def _get_socket(raw, _ctx) -> bytes:
    want = _id_param(raw)
    for _i, s in _cz.live_servers():
        for conn in list(getattr(s, "_connections", [])):
            if _cz.socket_id_for(conn, 0) == want:
                return ld(1, _socket_msg(want, conn))
        # listen sockets: the ids GetServer advertises must resolve too
        for port in getattr(s, "bound_ports", []):
            if _cz.socket_id_for(s, port) == want:
                return ld(1, _listen_socket_msg(want, s, port))
    raise AbortError(StatusCode.NOT_FOUND, f"no socket with id {want}")


def enable_channelz(server: Server) -> None:
    """Serve grpc.channelz.v1 on this server (wire-compatible subset)."""
    for name, fn in (("GetServers", _get_servers),
                     ("GetTopChannels", _get_top_channels),
                     ("GetServer", _get_server),
                     ("GetChannel", _get_channel),
                     ("GetServerSockets", _get_server_sockets),
                     ("GetSocket", _get_socket)):
        server.add_method(f"/{SERVICE}/{name}",
                          unary_unary_rpc_method_handler(fn))
