"""Python binding over the native C client — the latency fast path.

SURVEY.md §7 stage 7 prescribes "a Python layer over the C API (minimal
Cython/ctypes layer)" the way grpcio's Python rides its Cython-wrapped C
core (``src/python/grpcio/grpc/_cython``). tpurpc's default channel is
pure Python (rich: LB trees, retries, interceptors, h2 interop); this
module is the thin ctypes alternative for latency-critical clients — the
blocking call path runs entirely inside ``libtpurpc.so`` (one GIL release
per call, no Python-level framing), and honors ``GRPC_PLATFORM_TYPE``:
with ``RDMA_BP|BPEV|EVENT`` the native channel bootstraps the shm ring
data plane (ring_transport.h), so a Python process gets the
ring-beats-TCP small-RPC numbers the native micro-bench measures
(bench/results/micro_native_1core.log).

    from tpurpc.rpc.native_client import NativeChannel
    with NativeChannel("127.0.0.1", port) as ch:
        echo = ch.unary_unary("/pkg.Svc/Echo")
        reply = echo(b"payload", timeout=5.0)

Scope: unary + streaming calls, deadlines, status mapping, ping. Not
here (use the default Channel): LB policies, retries, interceptors, TLS,
h2 wire compat — this is deliberately the reference's "thin stub over the
C core" shape, not a second full client.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import Callable, Iterable, Optional

from tpurpc.obs import flight as _flight
from tpurpc.rpc.status import RpcError, StatusCode

_LIB = None
_LIB_LOCK = threading.Lock()


def _load():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.environ.get(
            "TPURPC_NATIVE_LIB",
            os.path.join(here, "native", "build", "libtpurpc.so"))
        lib = ctypes.CDLL(path)
        lib.tpr_channel_create.restype = ctypes.c_void_p
        lib.tpr_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                           ctypes.c_int]
        if hasattr(lib, "tpr_channel_create2"):  # absent in pre-round-4 .so
            lib.tpr_channel_create2.restype = ctypes.c_void_p
            lib.tpr_channel_create2.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.tpr_channel_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_channel_ping.restype = ctypes.c_int64
        lib.tpr_channel_ping.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tpr_unary_call.restype = ctypes.c_int
        lib.tpr_unary_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]
        if hasattr(lib, "tpr_unary_call_ex"):  # absent in pre-round-5 .so
            lib.tpr_unary_call_ex.restype = ctypes.c_int
            lib.tpr_unary_call_ex.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int)]
        lib.tpr_call_start.restype = ctypes.c_void_p
        lib.tpr_call_start.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_size_t, ctypes.c_int]
        lib.tpr_call_send.restype = ctypes.c_int
        lib.tpr_call_send.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_size_t, ctypes.c_int]
        lib.tpr_call_writes_done.restype = ctypes.c_int
        lib.tpr_call_writes_done.argtypes = [ctypes.c_void_p]
        lib.tpr_call_recv.restype = ctypes.c_int
        lib.tpr_call_recv.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.tpr_call_finish.restype = ctypes.c_int
        lib.tpr_call_finish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_size_t]
        lib.tpr_call_cancel.argtypes = [ctypes.c_void_p]
        lib.tpr_call_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        # completion-queue async surface (futures fast path)
        lib.tpr_cq_create.restype = ctypes.c_void_p
        lib.tpr_cq_create.argtypes = []
        lib.tpr_cq_next.restype = ctypes.c_int
        lib.tpr_cq_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(_TprEvent), ctypes.c_int]
        lib.tpr_cq_shutdown.argtypes = [ctypes.c_void_p]
        lib.tpr_cq_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_unary_call_cq.restype = ctypes.c_void_p
        lib.tpr_unary_call_cq.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p]
        # zero-copy send lease (the reference's SendZerocopy shape): gather
        # segments serialize DIRECTLY into the transport ring — the staging
        # join and the ctypes from_buffer_copy both disappear. Optional: a
        # pre-round-5 .so has no lease entry points.
        try:
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tpr_call_send_reserve2.restype = ctypes.c_int
            lib.tpr_call_send_reserve2.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
                ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t)]
            lib.tpr_call_send_commit.restype = ctypes.c_int
            lib.tpr_call_send_commit.argtypes = [ctypes.c_void_p]
            lib.tpr_call_send_abort.restype = ctypes.c_int
            lib.tpr_call_send_abort.argtypes = [ctypes.c_void_p]
            lib._tpr_has_lease = True
        except AttributeError:  # pre-round-6 .so: no fragment-aware lease
            lib._tpr_has_lease = False
        # rendezvous/ctrl-ring ledger (absent in a pre-ironclad .so)
        if hasattr(lib, "tpr_rdv_counters"):
            lib.tpr_rdv_counters.restype = None
            lib.tpr_rdv_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
            lib.tpr_rdv_counters_reset.restype = None
            lib.tpr_rdv_counters_reset.argtypes = []
        _LIB = lib
        return lib


#: native rdv ledger slot names, in the library's CounterIdx ABI order
#: (native/src/tpr_rdv.h) — index position IS the contract
RDV_COUNTER_NAMES = (
    "rdv_sent", "rdv_recv", "rdv_fallback", "rdv_bytes_sent",
    "rdv_bytes_recv", "rdv_refused", "ctrl_posts", "ctrl_kicks",
    "ctrl_records", "ctrl_frames", "host_copy_bytes", "pregrants")


def rdv_counters() -> Optional[dict]:
    """Process-wide native rendezvous/ctrl-ring ledger as a name→count
    dict, or None when the loaded .so predates the rendezvous plane."""
    lib = _load()
    if not hasattr(lib, "tpr_rdv_counters"):
        return None
    buf = (ctypes.c_uint64 * len(RDV_COUNTER_NAMES))()
    lib.tpr_rdv_counters(buf, len(RDV_COUNTER_NAMES))
    return dict(zip(RDV_COUNTER_NAMES, buf))


def rdv_counters_reset() -> bool:
    """Zero the native rdv ledger (test/bench isolation). False when the
    loaded .so has no rendezvous plane."""
    lib = _load()
    if not hasattr(lib, "tpr_rdv_counters_reset"):
        return False
    lib.tpr_rdv_counters_reset()
    return True


class _TprEvent(ctypes.Structure):
    """Mirror of tpr_event (native/include/tpurpc/client.h)."""

    _fields_ = [("type", ctypes.c_int),
                ("tag", ctypes.c_void_p),
                ("ok", ctypes.c_int),
                ("data", ctypes.POINTER(ctypes.c_uint8)),
                ("len", ctypes.c_size_t),
                ("status", ctypes.c_int),
                ("details", ctypes.c_char * 256)]


_EV_FINISH = 2  # TPR_EV_FINISH


def _u8(data) -> "ctypes.Array":
    # serializers may emit a gather list of segments (the tensor codec
    # does); the C API takes one buffer, so join — one copy, same price
    # the TLS path pays
    if isinstance(data, (list, tuple)):
        data = b"".join(data)
    view = memoryview(data).cast("B")
    return (ctypes.c_uint8 * len(view)).from_buffer_copy(view)


def _u8_zc(data) -> "tuple":
    """(pointer-arg, nbytes) for a synchronous C call, zero-copy where the
    buffer allows it: ``bytes`` pass their own internal buffer via a
    ``c_char_p`` cast (immutable + referenced by the caller's local for
    the whole call, so the pointer stays valid with the GIL released).
    The ``from_buffer_copy`` staging array was a WHOLE EXTRA PASS over
    every bulk payload — measured ~0.3 ms per 4 MiB message, the single
    biggest native-vs-python plane gap. Non-bytes fall back to the
    staging copy. Only safe for entry points that consume the buffer
    before returning (send/unary paths do: the rdv memcpy or the ring
    write happens inside the call)."""
    if isinstance(data, (list, tuple)):
        data = b"".join(data)
    if isinstance(data, bytes):
        return (ctypes.cast(ctypes.c_char_p(data),
                            ctypes.POINTER(ctypes.c_uint8)), len(data))
    buf = _u8(data)
    return buf, len(buf)


def _timeout_ms(timeout: Optional[float]) -> int:
    if timeout is None:
        return 0
    return max(1, int(timeout * 1000))


def _take_buf(lib, pptr, plen) -> bytes:
    try:
        return ctypes.string_at(pptr, plen.value) if plen.value else b""
    finally:
        if pptr:
            lib.tpr_buf_free(pptr)


class NativeCall:
    """A streaming call handle (thin ClientCall analog)."""

    def __init__(self, lib, call, on_close: Optional[Callable] = None):
        self._lib = lib
        self._call = call
        self._lock = threading.Lock()
        self._on_close = on_close  # NativeChannel op release (exactly once)

    #: lease path cut-in: below this a join+send is as cheap as the
    #: reserve/commit round trips, and control-plane messages stay on the
    #: battle-tested classic path
    _LEASE_MIN = 64 * 1024
    #: one ring message per frame — kMaxFramePayload (framing_common.h)
    _LEASE_FRAME = 1 << 20

    def write(self, data, end_stream: bool = False) -> None:
        if (getattr(self._lib, "_tpr_has_lease", False)
                and isinstance(data, (list, tuple))):
            segs = [v for v in (memoryview(s).cast("B") for s in data)
                    if len(v)]
            total = sum(len(v) for v in segs)
            if total >= self._LEASE_MIN and self._write_lease(
                    segs, total, end_stream):
                return
        buf, blen = _u8_zc(data)  # `data` local keeps the buffer alive
        if self._lib.tpr_call_send(self._call, buf, blen,
                                   1 if end_stream else 0) != 0:
            raise RpcError(StatusCode.UNAVAILABLE, "send failed")

    def _write_lease(self, segs, total: int, end_stream: bool) -> bool:
        """Gather ``segs`` straight into the transport ring via the
        zero-copy send lease (tpr_call_send_reserve/commit): one
        frame-sized reserve per ≤1 MiB chunk, segments copied in place
        with memoryview slice assignment, commit publishes. Returns False
        with NO bytes sent when the channel has no ring (first reserve
        fails — the classic path handles it); raises on a mid-message
        failure (the channel died; nothing can be un-sent)."""
        lib = self._lib
        p1 = ctypes.POINTER(ctypes.c_uint8)()
        l1 = ctypes.c_size_t()
        p2 = ctypes.POINTER(ctypes.c_uint8)()
        l2 = ctypes.c_size_t()
        sent = 0
        si = 0  # segment cursor
        so = 0  # offset within segs[si]
        # tpurpc-blackbox: the lease lifecycle in the flight ring — an
        # unmatched reserve in the tail is the watchdog's smoking gun for
        # a wedged ring write lock (the round-5 bug class, now observable)
        ftag = _flight.tag_for("nclease")
        while sent < total:
            n = min(total - sent, self._LEASE_FRAME)
            last = sent + n == total
            # non-final fragments carry MORE so the peer reassembles ONE
            # message; END_STREAM only ever rides the final fragment
            flags = (1 if end_stream else 0) if last else 2
            if lib.tpr_call_send_reserve2(
                    self._call, n, flags,
                    ctypes.byref(p1), ctypes.byref(l1),
                    ctypes.byref(p2), ctypes.byref(l2)) != 0:
                if sent == 0:
                    return False  # no ring under this channel: classic path
                raise RpcError(StatusCode.UNAVAILABLE, "send failed")
            try:
                _flight.emit(_flight.LEASE_RESERVE, ftag, n)
                # ≤2 wrap-split ring spans; fill from the segment stream
                for ptr, ln in ((p1, l1.value), (p2, l2.value)):
                    if not ln:
                        continue
                    dst = memoryview(ctypes.cast(
                        ptr, ctypes.POINTER(ctypes.c_uint8 * ln)).contents
                    ).cast("B")
                    off = 0
                    while off < ln:
                        seg = segs[si]
                        take = min(len(seg) - so, ln - off)
                        dst[off:off + take] = seg[so:so + take]
                        off += take
                        so += take
                        if so == len(seg):
                            si += 1
                            so = 0
            except BaseException:
                lib.tpr_call_send_abort(self._call)  # release write_mu
                _flight.emit(_flight.LEASE_ABORT, ftag, n)
                raise
            if lib.tpr_call_send_commit(self._call) != 0:
                _flight.emit(_flight.LEASE_ABORT, ftag, n)
                raise RpcError(StatusCode.UNAVAILABLE, "send failed")
            _flight.emit(_flight.LEASE_COMMIT, ftag, n)
            sent += n
        return True

    def writes_done(self) -> None:
        self._lib.tpr_call_writes_done(self._call)

    def read(self) -> Optional[bytes]:
        """Next response message, or None at end of stream/error
        (finish() distinguishes)."""
        pptr = ctypes.POINTER(ctypes.c_uint8)()
        plen = ctypes.c_size_t()
        r = self._lib.tpr_call_recv(self._call,
                                    ctypes.byref(pptr), ctypes.byref(plen))
        if r != 1:
            return None
        return _take_buf(self._lib, pptr, plen)

    def finish(self):
        details = ctypes.create_string_buffer(1024)
        code = self._lib.tpr_call_finish(self._call, details, 1024)
        return (StatusCode(code) if code in StatusCode._value2member_map_
                else StatusCode.UNKNOWN), details.value.decode(
                    "utf-8", "replace")

    def cancel(self) -> None:
        self._lib.tpr_call_cancel(self._call)

    def close(self) -> None:
        cb = None
        with self._lock:
            if self._call:
                self._lib.tpr_call_destroy(self._call)
                self._call = None
                cb, self._on_close = self._on_close, None
        if cb is not None:
            cb()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _CqDriver:
    """One completion queue + puller thread per channel: resolves
    ``unary.future()`` calls from tagged TPR_EV_FINISH completions — the
    grpcio ``.future()`` shape over the native CQ async API, so a Python
    client can keep many unary calls in flight on one connection (the
    micro-bench's pipelined mode, bench/results/micro_native_1core.log)."""

    def __init__(self, lib):
        import concurrent.futures  # stdlib Future is the contract

        self._lib = lib
        self._Future = concurrent.futures.Future
        self._cq = lib.tpr_cq_create()
        self._lock = threading.Lock()
        # tag -> entry {fut, call, des, done}; `call` is filled right after
        # tpr_unary_call_cq returns — the completion can race that store,
        # so whoever sees both `done` and `call` performs the destroy.
        self._pending: dict = {}
        self._next_tag = 1
        self._thread = threading.Thread(target=self._pull, daemon=True,
                                        name="tpurpc-native-cq")
        self._thread.start()

    def submit(self, ch, method_b: bytes, raw, timeout,
               deserializer) -> "concurrent.futures.Future":
        # before registering: a bad serializer output must not leak a
        # pending entry (close would stall); zero-copy — tpr_unary_call_cq
        # consumes the request buffer before it returns
        buf, blen = _u8_zc(raw)
        fut = self._Future()
        with self._lock:
            tag = self._next_tag
            self._next_tag += 1
            entry = {"fut": fut, "call": None, "des": deserializer,
                     "done": False}
            self._pending[tag] = entry
        call = self._lib.tpr_unary_call_cq(ch, method_b, buf, blen,
                                           _timeout_ms(timeout), self._cq,
                                           ctypes.c_void_p(tag))
        if not call:
            with self._lock:
                self._pending.pop(tag, None)
            exc = RpcError(StatusCode.UNAVAILABLE,
                           "call refused (channel dead or draining)")
            exc._tpurpc_preexec = True  # admission refusal: nothing sent
            raise exc
        destroy = None
        with self._lock:
            entry["call"] = call
            if entry["done"]:  # completion won the race; we own the destroy
                destroy = call
                self._pending.pop(tag, None)
        if destroy:
            self._lib.tpr_call_destroy(destroy)
        return fut

    def _pull(self):
        ev = _TprEvent()
        while True:
            rc = self._lib.tpr_cq_next(self._cq, ctypes.byref(ev), 1000)
            if rc == -1:
                return  # shut down and drained
            if rc != 1 or ev.type != _EV_FINISH:
                continue
            tag = ev.tag or 0
            body = b""
            if ev.data:
                body = ctypes.string_at(ev.data, ev.len) if ev.len else b""
                self._lib.tpr_buf_free(ev.data)
            destroy = None
            with self._lock:
                entry = self._pending.get(tag)
                if entry is None:
                    continue
                entry["done"] = True
                if entry["call"]:
                    destroy = entry["call"]
                    self._pending.pop(tag, None)
                # else: submit() still holds the race; it destroys
            if destroy:
                self._lib.tpr_call_destroy(destroy)
            fut, des = entry["fut"], entry["des"]
            if not fut.set_running_or_notify_cancel():
                continue  # user cancelled the Future; drop the result
            if ev.status == 0:
                try:
                    fut.set_result(des(body) if des else body)
                except Exception as exc:  # deserializer raised
                    fut.set_exception(exc)
            else:
                code = (StatusCode(ev.status)
                        if ev.status in StatusCode._value2member_map_
                        else StatusCode.UNKNOWN)
                fut.set_exception(RpcError(
                    code, ev.details.decode("utf-8", "replace")))

    def close(self, cancel_inflight: bool = True) -> bool:
        """Cancel in-flight calls, drain their completions, stop the
        puller, free the queue. Must run BEFORE tpr_channel_destroy —
        destroying a call touches its channel.

        Returns True iff teardown was CLEAN: every pending call drained
        (so its tpr_call_destroy already ran) and the puller thread
        exited. On False the caller must NOT destroy the channel — a
        starved puller (e.g. a slow user deserializer runs on this
        thread for sync .future() calls) may still call
        tpr_call_destroy on calls whose channel would then be freed."""
        if cancel_inflight:
            # Cancel UNDER the lock: the puller pops an entry (and later
            # destroys its call) while holding it, so a call still present
            # in _pending here cannot concurrently be freed under us.
            with self._lock:
                for e in self._pending.values():
                    if e["call"] and not e["done"]:
                        self._lib.tpr_call_cancel(e["call"])
        deadline = time.monotonic() + 10.0
        drained = False
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    drained = True
                    break
            time.sleep(0.01)
        self._lib.tpr_cq_shutdown(self._cq)
        self._thread.join(timeout=10.0)
        if not self._thread.is_alive():
            if not drained:
                # tpr_cq_next keeps draining queued events after shutdown,
                # so a slow (but finite) deserializer may have finished the
                # backlog between the drain-wait timeout and the join —
                # re-check rather than return the stale snapshot (which
                # would leak the channel for nothing).
                with self._lock:
                    drained = not self._pending
            self._lib.tpr_cq_destroy(self._cq)
            return drained
        # else: leak the cq — a wedged puller beats a use-after-free
        return False


class _InlineWindow:
    """Bounded multi-in-flight window for INLINE-READ channels, where the
    CQ async surface refuses (``tpr_unary_call_cq`` needs the reader
    thread). ``depth`` persistent daemon workers issue the blocking C calls
    — the native loop multiplexes concurrent streams on one connection
    (each blocking caller pumps or parks on the channel's cv), so this is
    genuine wire pipelining, not thread-per-call churn: the worker set is
    fixed and the depth+1'th submit blocks (window backpressure)."""

    def __init__(self, depth: int):
        import concurrent.futures
        import queue as _queue

        self._Future = concurrent.futures.Future
        self._jobs: "_queue.Queue" = _queue.Queue()
        self._depth = max(1, depth)
        self._window = threading.BoundedSemaphore(self._depth)
        self._workers = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"tpurpc-native-inline-{i}")
            for i in range(self._depth)]
        for w in self._workers:
            w.start()

    def submit(self, call_fn, request, timeout):
        self._window.acquire()  # backpressure: at most depth in flight
        fut = self._Future()
        self._jobs.put((call_fn, request, timeout, fut))
        return fut

    def _run(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            call_fn, request, timeout, fut = job
            try:
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(call_fn(request, timeout))
                except BaseException as exc:
                    fut.set_exception(exc)
            finally:
                self._window.release()

    def close(self) -> None:
        for _ in self._workers:
            self._jobs.put(None)


class NativeChannel:
    """ctypes channel over the native client loop (see module docstring)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0,
                 inline_read: bool = False, pipeline_depth: int = 16):
        self._lib = _load()
        self._cq_driver: Optional[_CqDriver] = None
        self._cq_lock = threading.Lock()
        self._cq_cond = threading.Condition(self._cq_lock)
        #: in-flight bound for .future() calls on inline-read channels
        #: (reader-thread channels bound in the C CQ instead)
        self._pipeline_depth = max(1, pipeline_depth)
        self._inline_window: Optional[_InlineWindow] = None
        #: native entries currently holding the raw channel pointer inside
        #: libtpurpc (blocking unary calls, pings, live NativeCall handles).
        #: close() must not tpr_channel_destroy until this drains — a call
        #: completing on another thread touches ch->streams in
        #: tpr_call_destroy (ASan-caught use-after-free, round 4).
        self._ops = 0
        # inline_read: the per-channel inline-read discipline (blocking
        # callers pump the ring; no reader thread — the lowest-latency
        # mode). The CQ async API (.future()) refuses on such channels.
        # inline_read=False takes tpr_channel_create, which OWNS the
        # TPURPC_NATIVE_INLINE_READ env default — one copy of that rule,
        # in C; the explicit flag needs create2 (older .so: fall back to
        # the env-defaulted entry rather than crash on version skew).
        if inline_read and hasattr(self._lib, "tpr_channel_create2"):
            self._ch = self._lib.tpr_channel_create2(
                host.encode(), int(port), _timeout_ms(connect_timeout), 1)
            #: what was ACTUALLY requested of the C loop (observability:
            #: bench artifacts record the discipline; the old-.so fallback
            #: below reports False even when inline was asked for)
            self.inline_read = True
        else:
            self._ch = self._lib.tpr_channel_create(
                host.encode(), int(port), _timeout_ms(connect_timeout))
            self.inline_read = False
        if not self._ch:
            raise RpcError(StatusCode.UNAVAILABLE,
                           f"native connect to {host}:{port} failed")

    def _driver(self) -> _CqDriver:
        with self._cq_lock:
            if not self._ch:  # close() swaps _ch under this same lock, so a
                # late future() can't resurrect a driver nothing will close
                exc = RpcError(StatusCode.UNAVAILABLE, "channel closed")
                exc._tpurpc_preexec = True
                raise exc
            if self._cq_driver is None:
                self._cq_driver = _CqDriver(self._lib)
            return self._cq_driver

    def _window(self) -> _InlineWindow:
        with self._cq_lock:
            if not self._ch:
                exc = RpcError(StatusCode.UNAVAILABLE, "channel closed")
                exc._tpurpc_preexec = True
                raise exc
            if self._inline_window is None:
                self._inline_window = _InlineWindow(self._pipeline_depth)
            return self._inline_window

    def _op_begin(self):
        """Claim the channel pointer for a native entry. The claim (not a
        bare pointer read) is what lets close() prove no other thread is
        inside the C loop before freeing the channel."""
        with self._cq_lock:
            if not self._ch:
                exc = RpcError(StatusCode.UNAVAILABLE, "channel closed")
                exc._tpurpc_preexec = True  # nothing entered the C loop
                raise exc
            self._ops += 1
            return self._ch

    def _op_end(self) -> None:
        with self._cq_cond:
            self._ops -= 1
            if self._ops == 0:
                self._cq_cond.notify_all()

    def _handle(self):
        """The live native handle; raises (instead of passing a freed/NULL
        pointer into C and segfaulting) once close() ran. For entries that
        BLOCK inside the C loop use _op_begin/_op_end instead, so close()
        can wait them out."""
        ch = self._ch
        if not ch:
            raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
        return ch

    # -- surface -------------------------------------------------------------

    def ping(self, timeout: float = 5.0) -> float:
        ch = self._op_begin()
        try:
            us = self._lib.tpr_channel_ping(ch, _timeout_ms(timeout))
        finally:
            self._op_end()
        if us < 0:
            raise RpcError(StatusCode.UNAVAILABLE, "ping failed")
        return us / 1e6

    def unary_unary(self, method: str,
                    request_serializer: Optional[Callable] = None,
                    response_deserializer: Optional[Callable] = None):
        mb = method.encode()
        lib = self._lib

        have_ex = hasattr(lib, "tpr_unary_call_ex")

        def call(request, timeout: Optional[float] = None):
            raw = (request_serializer(request) if request_serializer
                   else request)
            buf, blen = _u8_zc(raw)  # synchronous call: `buf` holds a ref
            pptr = ctypes.POINTER(ctypes.c_uint8)()
            plen = ctypes.c_size_t()
            details = ctypes.create_string_buffer(1024)
            preexec = ctypes.c_int(0)
            ch = self._op_begin()  # a closed channel raises; close() waits
            try:
                if have_ex:
                    code = lib.tpr_unary_call_ex(
                        ch, mb, buf, blen,
                        ctypes.byref(pptr), ctypes.byref(plen),
                        details, 1024, _timeout_ms(timeout),
                        ctypes.byref(preexec))
                else:
                    code = lib.tpr_unary_call(
                        ch, mb, buf, blen,
                        ctypes.byref(pptr), ctypes.byref(plen),
                        details, 1024, _timeout_ms(timeout))
            finally:
                self._op_end()
            if code != 0:
                text = details.value.decode("utf-8", "replace")
                exc = RpcError(
                    StatusCode(code) if code in StatusCode._value2member_map_
                    else StatusCode.UNKNOWN, text)
                # Machine-readable replay-safety verdict from the C loop
                # (tpr_unary_call_ex): True iff the failure provably
                # happened before the request fully left this process, so
                # replaying it can never double-execute a handler. Channel
                # consumers gate fallback on this attribute, never on the
                # human-readable details wording. Legacy shim: a
                # pre-round-5 .so has no preexec out-param, so its known
                # pre-exec wordings (tpr_unary_call's three early returns)
                # are the only signal left.
                exc._tpurpc_preexec = bool(preexec.value) if have_ex else any(
                    s in text for s in ("channel dead", "send failed"))
                raise exc
            body = _take_buf(lib, pptr, plen)
            return (response_deserializer(body) if response_deserializer
                    else body)

        def future(request, timeout: Optional[float] = None):
            """grpcio's ``.future()`` shape: returns a concurrent.futures
            .Future resolving to the response (or raising RpcError), with
            the call pipelined through the channel's completion queue —
            many can be in flight at once on one connection. On
            INLINE-READ channels (no reader thread, so no CQ) the same
            multi-in-flight contract rides a bounded worker window over
            the blocking entry: the C loop multiplexes the concurrent
            streams on the one connection either way."""
            if self.inline_read:
                return self._window().submit(call, request, timeout)
            raw = (request_serializer(request) if request_serializer
                   else request)
            drv = self._driver()
            ch = self._op_begin()  # guard the submit window; the call's
            try:                   # lifetime after that is the driver's
                return drv.submit(ch, mb, raw, timeout,
                                  response_deserializer)
            finally:
                self._op_end()

        call.future = future
        return call

    def start_call(self, method: str, timeout: Optional[float] = None,
                   metadata=None) -> NativeCall:
        """Start a streaming call. ``metadata`` is an optional list of
        ``(key, value)`` text pairs shipped through ``tpr_call_start``'s
        flat ``k,v,k,v`` array — the seam the tpurpc-scope trace context
        (``tpurpc-trace``) rides on the native plane."""
        md_arr, n_md = None, 0
        if metadata:
            flat = []
            for k, v in metadata:
                flat.append(str(k).encode())
                flat.append(v if isinstance(v, bytes) else str(v).encode())
            md_arr = (ctypes.c_char_p * len(flat))(*flat)
            n_md = len(metadata)
        ch = self._op_begin()  # held for the NativeCall's whole lifetime:
        try:                   # its tpr_call_* entries all touch the channel
            c = self._lib.tpr_call_start(ch, method.encode(), md_arr,
                                         n_md, _timeout_ms(timeout))
            if not c:
                raise RpcError(StatusCode.UNAVAILABLE, "call start failed")
            return NativeCall(self._lib, c, on_close=self._op_end)
        except BaseException:
            self._op_end()
            raise

    def stream_stream(self, method: str):
        """Bidi helper with the Channel-compatible iterator shape."""

        def call(request_iterator: Iterable, timeout: Optional[float] = None):
            nc = self.start_call(method, timeout)
            app_exc: list = []

            def run():
                try:
                    for item in request_iterator:
                        nc.write(item)
                    nc.writes_done()
                except RpcError:
                    pass  # reader surfaces the status
                except BaseException as exc:  # the app's iterator raised:
                    # half-close never happens — cancel so the reader (and
                    # the server's handler) unblock, and surface the error
                    app_exc.append(exc)
                    nc.cancel()

            t = threading.Thread(target=run, daemon=True)
            t.start()
            try:
                while True:
                    msg = nc.read()
                    if msg is None:
                        break
                    yield msg
            finally:
                if t.is_alive():
                    # early consumer exit with requests still flowing: RST
                    # first (the server drops the stream, backpressure
                    # releases, the blocked write fails fast), THEN join —
                    # destroying the call under a live writer thread is a
                    # native use-after-free
                    nc.cancel()
                t.join()
                code, details = nc.finish()
                nc.close()
                if app_exc:
                    raise app_exc[0]
                if code is not StatusCode.OK:
                    raise RpcError(code, details)

        return call

    def close(self) -> None:
        with self._cq_cond:
            ch, self._ch = self._ch, None
            drv, self._cq_driver = self._cq_driver, None
            win, self._inline_window = self._inline_window, None
            # Wait out native entries still holding the raw pointer
            # (blocking unary calls / pings / live NativeCall handles on
            # other threads): destroying under them is the ASan-caught
            # use-after-free. _ch is already None, so no NEW entry can
            # begin while we wait.
            deadline = time.monotonic() + 10.0
            while self._ops > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cq_cond.wait(remaining)
            ops_drained = self._ops == 0
        if win is not None:
            win.close()  # idle workers exit; busy ones were waited out above
        if ch:
            # CQ teardown first: destroying a call touches its channel, so
            # every future's call must be destroyed before the channel is.
            # If the driver could not prove a clean drain (wedged/starved
            # puller still holding live calls), leak the channel too — the
            # same leak-beats-use-after-free policy the cq itself uses.
            if drv is not None and not drv.close():
                return
            if not ops_drained:
                return  # leak: an entry is still inside the C loop
            self._lib.tpr_channel_destroy(ch)

    def __del__(self):
        # safety net: a dropped channel must not leak the native reader
        # thread + fd (+ shm ring on ring platforms)
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
