"""Python binding over the native C client — the latency fast path.

SURVEY.md §7 stage 7 prescribes "a Python layer over the C API (minimal
Cython/ctypes layer)" the way grpcio's Python rides its Cython-wrapped C
core (``src/python/grpcio/grpc/_cython``). tpurpc's default channel is
pure Python (rich: LB trees, retries, interceptors, h2 interop); this
module is the thin ctypes alternative for latency-critical clients — the
blocking call path runs entirely inside ``libtpurpc.so`` (one GIL release
per call, no Python-level framing), and honors ``GRPC_PLATFORM_TYPE``:
with ``RDMA_BP|BPEV|EVENT`` the native channel bootstraps the shm ring
data plane (ring_transport.h), so a Python process gets the
ring-beats-TCP small-RPC numbers the native micro-bench measures
(bench/results/micro_native_1core.log).

    from tpurpc.rpc.native_client import NativeChannel
    with NativeChannel("127.0.0.1", port) as ch:
        echo = ch.unary_unary("/pkg.Svc/Echo")
        reply = echo(b"payload", timeout=5.0)

Scope: unary + streaming calls, deadlines, status mapping, ping. Not
here (use the default Channel): LB policies, retries, interceptors, TLS,
h2 wire compat — this is deliberately the reference's "thin stub over the
C core" shape, not a second full client.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Callable, Iterable, Optional

from tpurpc.rpc.status import RpcError, StatusCode

_LIB = None
_LIB_LOCK = threading.Lock()


def _load():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.environ.get(
            "TPURPC_NATIVE_LIB",
            os.path.join(here, "native", "build", "libtpurpc.so"))
        lib = ctypes.CDLL(path)
        lib.tpr_channel_create.restype = ctypes.c_void_p
        lib.tpr_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                           ctypes.c_int]
        lib.tpr_channel_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_channel_ping.restype = ctypes.c_int64
        lib.tpr_channel_ping.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tpr_unary_call.restype = ctypes.c_int
        lib.tpr_unary_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]
        lib.tpr_call_start.restype = ctypes.c_void_p
        lib.tpr_call_start.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_size_t, ctypes.c_int]
        lib.tpr_call_send.restype = ctypes.c_int
        lib.tpr_call_send.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_size_t, ctypes.c_int]
        lib.tpr_call_writes_done.restype = ctypes.c_int
        lib.tpr_call_writes_done.argtypes = [ctypes.c_void_p]
        lib.tpr_call_recv.restype = ctypes.c_int
        lib.tpr_call_recv.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.tpr_call_finish.restype = ctypes.c_int
        lib.tpr_call_finish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_size_t]
        lib.tpr_call_cancel.argtypes = [ctypes.c_void_p]
        lib.tpr_call_destroy.argtypes = [ctypes.c_void_p]
        lib.tpr_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _LIB = lib
        return lib


def _u8(data) -> "ctypes.Array":
    # serializers may emit a gather list of segments (the tensor codec
    # does); the C API takes one buffer, so join — one copy, same price
    # the TLS path pays
    if isinstance(data, (list, tuple)):
        data = b"".join(data)
    view = memoryview(data).cast("B")
    return (ctypes.c_uint8 * len(view)).from_buffer_copy(view)


def _timeout_ms(timeout: Optional[float]) -> int:
    if timeout is None:
        return 0
    return max(1, int(timeout * 1000))


def _take_buf(lib, pptr, plen) -> bytes:
    try:
        return ctypes.string_at(pptr, plen.value) if plen.value else b""
    finally:
        if pptr:
            lib.tpr_buf_free(pptr)


class NativeCall:
    """A streaming call handle (thin ClientCall analog)."""

    def __init__(self, lib, call):
        self._lib = lib
        self._call = call
        self._lock = threading.Lock()

    def write(self, data, end_stream: bool = False) -> None:
        buf = _u8(data)
        if self._lib.tpr_call_send(self._call, buf, len(buf),
                                   1 if end_stream else 0) != 0:
            raise RpcError(StatusCode.UNAVAILABLE, "send failed")

    def writes_done(self) -> None:
        self._lib.tpr_call_writes_done(self._call)

    def read(self) -> Optional[bytes]:
        """Next response message, or None at end of stream/error
        (finish() distinguishes)."""
        pptr = ctypes.POINTER(ctypes.c_uint8)()
        plen = ctypes.c_size_t()
        r = self._lib.tpr_call_recv(self._call,
                                    ctypes.byref(pptr), ctypes.byref(plen))
        if r != 1:
            return None
        return _take_buf(self._lib, pptr, plen)

    def finish(self):
        details = ctypes.create_string_buffer(1024)
        code = self._lib.tpr_call_finish(self._call, details, 1024)
        return (StatusCode(code) if code in StatusCode._value2member_map_
                else StatusCode.UNKNOWN), details.value.decode(
                    "utf-8", "replace")

    def cancel(self) -> None:
        self._lib.tpr_call_cancel(self._call)

    def close(self) -> None:
        with self._lock:
            if self._call:
                self._lib.tpr_call_destroy(self._call)
                self._call = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeChannel:
    """ctypes channel over the native client loop (see module docstring)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        self._lib = _load()
        self._ch = self._lib.tpr_channel_create(
            host.encode(), int(port), _timeout_ms(connect_timeout))
        if not self._ch:
            raise RpcError(StatusCode.UNAVAILABLE,
                           f"native connect to {host}:{port} failed")

    def _handle(self):
        """The live native handle; raises (instead of passing a freed/NULL
        pointer into C and segfaulting) once close() ran. Closing with
        calls in flight is unsupported, like destroying a grpcio channel
        mid-call."""
        ch = self._ch
        if not ch:
            raise RpcError(StatusCode.UNAVAILABLE, "channel closed")
        return ch

    # -- surface -------------------------------------------------------------

    def ping(self, timeout: float = 5.0) -> float:
        us = self._lib.tpr_channel_ping(self._handle(), _timeout_ms(timeout))
        if us < 0:
            raise RpcError(StatusCode.UNAVAILABLE, "ping failed")
        return us / 1e6

    def unary_unary(self, method: str,
                    request_serializer: Optional[Callable] = None,
                    response_deserializer: Optional[Callable] = None):
        mb = method.encode()
        lib = self._lib

        def call(request, timeout: Optional[float] = None):
            ch = self._handle()  # per-call: a closed channel raises
            raw = (request_serializer(request) if request_serializer
                   else request)
            buf = _u8(raw)
            pptr = ctypes.POINTER(ctypes.c_uint8)()
            plen = ctypes.c_size_t()
            details = ctypes.create_string_buffer(1024)
            code = lib.tpr_unary_call(ch, mb, buf, len(buf),
                                      ctypes.byref(pptr), ctypes.byref(plen),
                                      details, 1024, _timeout_ms(timeout))
            if code != 0:
                raise RpcError(
                    StatusCode(code) if code in StatusCode._value2member_map_
                    else StatusCode.UNKNOWN,
                    details.value.decode("utf-8", "replace"))
            body = _take_buf(lib, pptr, plen)
            return (response_deserializer(body) if response_deserializer
                    else body)

        return call

    def start_call(self, method: str,
                   timeout: Optional[float] = None) -> NativeCall:
        c = self._lib.tpr_call_start(self._handle(), method.encode(), None,
                                     0, _timeout_ms(timeout))
        if not c:
            raise RpcError(StatusCode.UNAVAILABLE, "call start failed")
        return NativeCall(self._lib, c)

    def stream_stream(self, method: str):
        """Bidi helper with the Channel-compatible iterator shape."""

        def call(request_iterator: Iterable, timeout: Optional[float] = None):
            nc = self.start_call(method, timeout)
            app_exc: list = []

            def run():
                try:
                    for item in request_iterator:
                        nc.write(item)
                    nc.writes_done()
                except RpcError:
                    pass  # reader surfaces the status
                except BaseException as exc:  # the app's iterator raised:
                    # half-close never happens — cancel so the reader (and
                    # the server's handler) unblock, and surface the error
                    app_exc.append(exc)
                    nc.cancel()

            t = threading.Thread(target=run, daemon=True)
            t.start()
            try:
                while True:
                    msg = nc.read()
                    if msg is None:
                        break
                    yield msg
            finally:
                if t.is_alive():
                    # early consumer exit with requests still flowing: RST
                    # first (the server drops the stream, backpressure
                    # releases, the blocked write fails fast), THEN join —
                    # destroying the call under a live writer thread is a
                    # native use-after-free
                    nc.cancel()
                t.join()
                code, details = nc.finish()
                nc.close()
                if app_exc:
                    raise app_exc[0]
                if code is not StatusCode.OK:
                    raise RpcError(code, details)

        return call

    def close(self) -> None:
        ch, self._ch = self._ch, None
        if ch:
            self._lib.tpr_channel_destroy(ch)

    def __del__(self):
        # safety net: a dropped channel must not leak the native reader
        # thread + fd (+ shm ring on ring platforms)
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
