"""grpc.lb.v1 wire codec — stock grpclb interop for the look-aside LB.

The reference's grpclb policy (``lb_policy/grpclb/grpclb.cc``) speaks the
``grpc.lb.v1.LoadBalancer/BalanceLoad`` bidi stream defined in
``src/proto/grpc/lb/v1/load_balancer.proto``. tpurpc's look-aside module
(:mod:`tpurpc.rpc.lookaside`) carries the same control loop over a
tpurpc-native JSON protocol; this module adds the standard protobuf wire
so stock grpclb clients can subscribe to a tpurpc balancer and a tpurpc
watcher can consume a stock balancer. Hand-rolled field codec in the
style of :mod:`tpurpc.rpc.health` (no generated code needed).

Message subset (fields we produce/consume; unknown fields are skipped):

    LoadBalanceRequest  { InitialLoadBalanceRequest initial_request = 1; }
    InitialLoadBalanceRequest { string name = 1; }
    LoadBalanceResponse { InitialLoadBalanceResponse initial_response = 1;
                          ServerList server_list = 2;
                          FallbackResponse fallback_response = 3; }
    ServerList { repeated Server servers = 1; }
    Server { bytes ip_address = 1;     // 4 or 16 bytes, network order
             int32 port = 2;
             string load_balance_token = 3;
             bool drop = 4; }

grpc.lb.v1 addresses are IPs, not hostnames: list entries that do not
parse as IPv4/IPv6 are skipped on encode (traced), matching what a stock
balancer could legally emit.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Sequence, Tuple

from tpurpc.rpc.lookaside import trace_lb  # one registry slot, one knob
from tpurpc.wire.protowire import fields, ld, vf

SERVICE = "grpc.lb.v1.LoadBalancer"
METHOD = f"/{SERVICE}/BalanceLoad"


def _split_hostport(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host.strip("[]"), int(port)


def encode_initial_request(name: str) -> bytes:
    """LoadBalanceRequest{initial_request{name}} — the subscribe message a
    grpclb client opens the stream with."""
    return ld(1, ld(1, name.encode()))


def decode_request(buf) -> Optional[str]:
    """Returns the subscribed name for an initial_request, None for
    client_stats / unknown (grpclb clients send stats on the same stream;
    a balancer ignores what it doesn't consume)."""
    for fno, wt, val in fields(bytes(buf)):
        if fno == 1 and wt == 2:
            for ifno, iwt, ival in fields(val):
                if ifno == 1 and iwt == 2:
                    return ival.decode("utf-8", "replace")
            return ""  # initial_request with no name: subscribe to default
    return None


def encode_initial_response(report_interval_s: float = 0.0) -> bytes:
    """LoadBalanceResponse{initial_response{...}} — sent once at stream
    start. ``report_interval_s > 0`` asks the client to stream ClientStats
    on that cadence (field 2, a google.protobuf.Duration)."""
    inner = b""
    if report_interval_s > 0:
        secs = int(report_interval_s)
        nanos = int(round((report_interval_s - secs) * 1e9))
        if nanos >= 1_000_000_000:  # round() carry: Duration caps nanos
            secs += 1
            nanos -= 1_000_000_000
        inner = ld(2, vf(1, secs) + vf(2, nanos))
    return ld(1, inner)


def encode_client_stats(started: int, finished: int,
                        known_received: int) -> bytes:
    """LoadBalanceRequest{client_stats} — the load report a grpclb client
    streams back when the balancer requested an interval. Counts are
    DELTAS since the previous report (grpclb accounting)."""
    return ld(2, vf(2, started) + vf(3, finished) + vf(7, known_received))


def decode_client_stats(buf) -> Optional[dict]:
    """Returns {"started", "finished", "known_received"} for a
    client_stats request, else None (initial_request / unknown)."""
    for fno, wt, val in fields(bytes(buf)):
        if fno == 2 and wt == 2:
            out = {"started": 0, "finished": 0, "known_received": 0}
            for sfno, swt, sval in fields(val):
                if swt != 0:
                    continue
                if sfno == 2:
                    out["started"] = sval
                elif sfno == 3:
                    out["finished"] = sval
                elif sfno == 7:
                    out["known_received"] = sval
            return out
    return None


def encode_server_list(addrs: Sequence[str]) -> bytes:
    """LoadBalanceResponse{server_list} from "ip:port" strings."""
    servers = b""
    for addr in addrs:
        try:
            host, port = _split_hostport(addr)
        except ValueError:
            trace_lb.log("grpc.lb.v1: skipping unparsable address %r", addr)
            continue
        packed = None
        for fam in (socket.AF_INET, socket.AF_INET6):
            try:
                packed = socket.inet_pton(fam, host)
                break
            except OSError:
                continue
        if packed is None:
            trace_lb.log("grpc.lb.v1: skipping non-IP address %r "
                         "(the wire carries packed IPs)", addr)
            continue
        servers += ld(1, ld(1, packed) + vf(2, port))
    return ld(2, servers)


def decode_response(buf) -> Tuple[str, object]:
    """Returns ("initial", report_interval_seconds), ("server_list",
    ["ip:port", ...]), ("fallback", None), or ("unknown", None)."""
    for fno, wt, val in fields(bytes(buf)):
        if fno == 1 and wt == 2:
            interval = 0.0
            for ifno, iwt, ival in fields(val):
                if ifno == 2 and iwt == 2:  # Duration{seconds=1, nanos=2}
                    secs = nanos = 0
                    for dfno, dwt, dval in fields(ival):
                        if dfno == 1 and dwt == 0:
                            secs = dval
                        elif dfno == 2 and dwt == 0:
                            nanos = dval
                    interval = secs + nanos / 1e9
            return "initial", interval
        if fno == 3 and wt == 2:
            return "fallback", None
        if fno == 2 and wt == 2:
            out: List[str] = []
            for sfno, swt, sval in fields(val):
                if sfno != 1 or swt != 2:
                    continue
                ip = b""
                port = 0
                drop = False
                for ffno, fwt, fval in fields(sval):
                    if ffno == 1 and fwt == 2:
                        ip = fval
                    elif ffno == 2 and fwt == 0:
                        port = fval
                    elif ffno == 4 and fwt == 0:
                        drop = bool(fval)
                if drop or not ip:
                    continue  # drop-entries steer load shedding, not dialing
                if len(ip) == 4:
                    out.append(f"{socket.inet_ntop(socket.AF_INET, ip)}:{port}")
                elif len(ip) == 16:
                    out.append(
                        f"[{socket.inet_ntop(socket.AF_INET6, ip)}]:{port}")
            return "server_list", out
    return "unknown", None


__all__ = ["SERVICE", "METHOD", "encode_initial_request", "decode_request",
           "encode_initial_response", "encode_server_list",
           "decode_response", "encode_client_stats", "decode_client_stats"]
