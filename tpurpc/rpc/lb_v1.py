"""grpc.lb.v1 wire codec — stock grpclb interop for the look-aside LB.

The reference's grpclb policy (``lb_policy/grpclb/grpclb.cc``) speaks the
``grpc.lb.v1.LoadBalancer/BalanceLoad`` bidi stream defined in
``src/proto/grpc/lb/v1/load_balancer.proto``. tpurpc's look-aside module
(:mod:`tpurpc.rpc.lookaside`) carries the same control loop over a
tpurpc-native JSON protocol; this module adds the standard protobuf wire
so stock grpclb clients can subscribe to a tpurpc balancer and a tpurpc
watcher can consume a stock balancer. Hand-rolled field codec in the
style of :mod:`tpurpc.rpc.health` (no generated code needed).

Message subset (fields we produce/consume; unknown fields are skipped):

    LoadBalanceRequest  { InitialLoadBalanceRequest initial_request = 1; }
    InitialLoadBalanceRequest { string name = 1; }
    LoadBalanceResponse { InitialLoadBalanceResponse initial_response = 1;
                          ServerList server_list = 2;
                          FallbackResponse fallback_response = 3; }
    ServerList { repeated Server servers = 1; }
    Server { bytes ip_address = 1;     // 4 or 16 bytes, network order
             int32 port = 2;
             string load_balance_token = 3;
             bool drop = 4; }

grpc.lb.v1 addresses are IPs, not hostnames: list entries that do not
parse as IPv4/IPv6 are skipped on encode (traced), matching what a stock
balancer could legally emit.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Sequence, Tuple

from tpurpc.rpc.lookaside import trace_lb  # one registry slot, one knob
from tpurpc.wire.protowire import fields, ld, vf

SERVICE = "grpc.lb.v1.LoadBalancer"
METHOD = f"/{SERVICE}/BalanceLoad"


def _split_hostport(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host.strip("[]"), int(port)


def encode_initial_request(name: str) -> bytes:
    """LoadBalanceRequest{initial_request{name}} — the subscribe message a
    grpclb client opens the stream with."""
    return ld(1, ld(1, name.encode()))


def decode_request(buf) -> Optional[str]:
    """Returns the subscribed name for an initial_request, None for
    client_stats / unknown (grpclb clients send stats on the same stream;
    a balancer ignores what it doesn't consume)."""
    for fno, wt, val in fields(bytes(buf)):
        if fno == 1 and wt == 2:
            for ifno, iwt, ival in fields(val):
                if ifno == 1 and iwt == 2:
                    return ival.decode("utf-8", "replace")
            return ""  # initial_request with no name: subscribe to default
    return None


def encode_initial_response() -> bytes:
    """LoadBalanceResponse{initial_response{}} — sent once at stream start
    (no client-stats interval: we don't request load reports)."""
    return ld(1, b"")


def encode_server_list(addrs: Sequence[str]) -> bytes:
    """LoadBalanceResponse{server_list} from "ip:port" strings."""
    servers = b""
    for addr in addrs:
        try:
            host, port = _split_hostport(addr)
        except ValueError:
            trace_lb.log("grpc.lb.v1: skipping unparsable address %r", addr)
            continue
        packed = None
        for fam in (socket.AF_INET, socket.AF_INET6):
            try:
                packed = socket.inet_pton(fam, host)
                break
            except OSError:
                continue
        if packed is None:
            trace_lb.log("grpc.lb.v1: skipping non-IP address %r "
                         "(the wire carries packed IPs)", addr)
            continue
        servers += ld(1, ld(1, packed) + vf(2, port))
    return ld(2, servers)


def decode_response(buf) -> Tuple[str, Optional[List[str]]]:
    """Returns ("initial", None), ("server_list", ["ip:port", ...]),
    ("fallback", None), or ("unknown", None)."""
    for fno, wt, val in fields(bytes(buf)):
        if fno == 1 and wt == 2:
            return "initial", None
        if fno == 3 and wt == 2:
            return "fallback", None
        if fno == 2 and wt == 2:
            out: List[str] = []
            for sfno, swt, sval in fields(val):
                if sfno != 1 or swt != 2:
                    continue
                ip = b""
                port = 0
                drop = False
                for ffno, fwt, fval in fields(sval):
                    if ffno == 1 and fwt == 2:
                        ip = fval
                    elif ffno == 2 and fwt == 0:
                        port = fval
                    elif ffno == 4 and fwt == 0:
                        drop = bool(fval)
                if drop or not ip:
                    continue  # drop-entries steer load shedding, not dialing
                if len(ip) == 4:
                    out.append(f"{socket.inet_ntop(socket.AF_INET, ip)}:{port}")
                elif len(ip) == 16:
                    out.append(
                        f"[{socket.inet_ntop(socket.AF_INET6, ip)}]:{port}")
            return "server_list", out
    return "unknown", None


__all__ = ["SERVICE", "METHOD", "encode_initial_request", "decode_request",
           "encode_initial_response", "encode_server_list",
           "decode_response"]
