"""tpurpc-manycore: shard the server data plane into per-core workers.

Every number the repo produced through PR 6 was single-core physics — PR 3
measured the serving core at 0% idle at depth 1, so 935 QPS was a one-core
ceiling, not a transport ceiling. The scale-out unit here is a worker
PROCESS, which buys three things at once:

* **one poller + ring set per worker, no cross-shard locking, by
  construction** — each worker owns its :class:`~tpurpc.core.poller.Poller`,
  pair pool, rings, thread pool, and batcher in its own address space (the
  RDMAbox lesson, arXiv:2104.12197: per-core queue/MR placement dominates
  throughput for memory-intensive RPC);
* **real core scaling** — CPython's GIL caps what N threads in one process
  can do to the Python framing path; N processes scale with the host;
* **honest failure units** — a shard that crashes takes exactly its own
  connections (clients see UNAVAILABLE and redial onto a live shard) and
  its telemetry VANISHES from the aggregated scrape instead of freezing.

Listener sharding comes in two flavors (the tentpole's part 1):

* ``listener="reuseport"`` (default) — every worker binds the serving port
  with ``SO_REUSEPORT``; the kernel spreads accepted connections across the
  listening workers with no supervisor in the accept path (RDMAvisor's
  shared-daemon multiplexing, arXiv:1802.01870, done by the kernel).
* ``listener="handoff"`` — the supervisor owns the listen socket and passes
  each accepted fd to a worker over its ``SOCK_SEQPACKET`` control channel
  (``SCM_RIGHTS``), round-robin or least-loaded on the workers' streamed
  load reports (the PR 6 load signals: transport in-flight + batcher
  depth). For platforms/hosts where REUSEPORT spread is unavailable or the
  operator wants load-aware placement.

Workers are forked, not spawned: the build callable (with its registered
handlers, model builders, closures) runs post-fork in the child, so
arbitrary servers shard without an import-path contract. The price is
post-fork hygiene — :func:`_postfork_worker_init` rebuilds every process
singleton the child inherited (poller, pair pool, timer wheel, metrics
registry with fresh locks and fleet membership, flight ring, watchdog,
channelz) so the worker starts with ITS truth, not the supervisor's.

Ring sizing is per-shard cache-resident (tentpole part 2): round 5 measured
*smaller* rings running *faster* (the working-set effect), so unless the
operator pins ``TPURPC_SHARD_RING_BUFFER_SIZE_KB``, each worker scales the
configured ring size down by the shard count — N shards share the LLC the
one big ring used to monopolize.

Observability: each worker runs a loopback scrape listener; the supervisor
broadcasts the peer map, and :mod:`tpurpc.obs.shard` makes any worker
answer ``GET /metrics`` (flight, stalls, healthz) with the AGGREGATED,
shard-tagged view. See ARCHITECTURE.md §16.
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from tpurpc.analysis.locks import make_lock
from tpurpc.obs import flight as _flight
from tpurpc.utils.trace import TraceFlag

trace_shard = TraceFlag("shard")

_SUP_TAG = _flight.tag_for("shard-supervisor")

#: control-channel message cap (SOCK_SEQPACKET: one recv = one message)
_CTRL_MSG_BYTES = 65536


# ---------------------------------------------------------------------------
# post-fork hygiene
# ---------------------------------------------------------------------------

def _postfork_worker_init(shard_id: int, n_shards: int) -> None:
    """Rebuild inherited process singletons in a freshly forked worker.

    Threads do not survive a fork, but their objects and (worst case) their
    held locks do: every singleton below is REPLACED — fresh lock objects,
    fresh state — rather than reset through machinery that might block on a
    lock a dead thread still holds. Order matters only for config (the ring
    sizing must land before anything reads it)."""
    import weakref

    # 1. per-shard cache-resident rings (round-5 working-set effect): N
    # workers share the LLC one ring used to own — scale the configured
    # size down by the shard count unless the operator pinned one.
    from tpurpc.utils import config as _cfg
    from tpurpc.utils.config import env_lookup

    pinned = env_lookup("TPURPC_SHARD_RING_BUFFER_SIZE_KB")[1]
    if pinned is not None:
        os.environ["TPURPC_RING_BUFFER_SIZE_KB"] = pinned
    elif n_shards > 1:
        base = _cfg.Config.from_env().ring_buffer_size_kb
        os.environ["TPURPC_RING_BUFFER_SIZE_KB"] = str(
            max(256, base // n_shards))
    _cfg.set_config(None)

    # 2. transport singletons: fresh locks, no inherited instances
    from tpurpc.core.poller import PairPool, Poller

    Poller._instance_lock = make_lock("Poller._instance_lock")
    Poller._instance = None
    PairPool._instance_lock = make_lock("PairPool._instance_lock")
    PairPool._instance = None

    from tpurpc.utils import timers as _timers

    _timers.TimerWheel._instance_lock = make_lock(
        "TimerWheel._instance_lock")
    _timers.TimerWheel._instance = None

    # 3. telemetry: this worker's registry must describe THIS worker.
    # Counters zero; fleet gauges drop the supervisor's (inert, forked)
    # objects — the weakref-death contract, enforced at the fork boundary.
    from tpurpc.obs import metrics as _metrics

    reg = _metrics.registry()
    reg._lock = make_lock("MetricsRegistry._lock")
    for m in reg.metrics().values():
        if isinstance(m, _metrics.FleetGauge):
            m._lock = make_lock("FleetGauge._lock")
            m._refs = weakref.WeakSet()
            continue
        if hasattr(m, "_lock"):
            m._lock = make_lock("Metric._lock")
        m.reset()

    from tpurpc.obs import profiler as _profiler
    from tpurpc.obs import shard as _obs_shard
    from tpurpc.obs import watchdog as _watchdog

    _flight.postfork_restart()
    _watchdog.postfork_reset()
    _profiler.postfork_reset()  # tpurpc-lens: supervisor samples are not ours
    # tpurpc-argus: the inherited tsdb rings hold the SUPERVISOR's history
    # and the slo evaluator thread died in the fork — fresh instances
    # (Server.start in the worker's build restarts both)
    try:
        from tpurpc.obs import slo as _slo
        from tpurpc.obs import tsdb as _tsdb

        _tsdb.postfork_reset()
        _slo.postfork_reset()
    except Exception:
        pass
    # tpurpc-odyssey: the inherited sequence ledgers are the supervisor's
    try:
        from tpurpc.obs import odyssey as _ody

        _ody.postfork_reset()
    except Exception:
        pass
    _obs_shard.set_identity(shard_id, n_shards)

    from tpurpc.rpc import channelz as _channelz

    _channelz._lock = make_lock("channelz._lock")
    _channelz._servers = weakref.WeakSet()
    _channelz._channels = weakref.WeakSet()

    try:  # tracing buffers: supervisor spans are not this worker's
        from tpurpc.obs import tracing as _tracing

        _tracing._lock = make_lock("tracing._lock")
        _tracing._pending = {}
        _tracing._spans.clear()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# worker main (runs in the forked child, never returns)
# ---------------------------------------------------------------------------

def _ctrl_send(ctrl: socket.socket, obj: dict) -> None:
    try:
        ctrl.send(json.dumps(obj).encode())
    except OSError:
        pass  # supervisor gone; the worker lives until told otherwise


def _worker_main(ctrl: socket.socket, shard_id: int, n_shards: int,
                 build: Callable[[int], object], mode: str,
                 host: str, port: int) -> None:
    _postfork_worker_init(shard_id, n_shards)
    try:
        srv = build(shard_id)
        srv.start()
        bound = None
        if mode == "reuseport":
            bound = srv.add_insecure_port(f"{host}:{port}", reuseport=True)
        from tpurpc.obs import scrape as _scrape

        _http, scrape_port = _scrape.start_http_server()
    except Exception as exc:
        _ctrl_send(ctrl, {"fatal": repr(exc)})
        os._exit(1)
    _flight.emit(_flight.SHARD_START, 0, shard_id, n_shards)
    _ctrl_send(ctrl, {"ready": shard_id, "scrape_port": scrape_port,
                      "port": bound, "pid": os.getpid()})

    def _load() -> int:
        n = srv.inflight_requests()
        extra = getattr(srv, "_load_extra", None)
        if extra is not None:
            try:
                n += int(extra())
            except Exception:
                pass
        return n

    ctrl.settimeout(0.05)
    last_load = -1
    while True:
        try:
            data, fds, _flags, _addr = socket.recv_fds(
                ctrl, _CTRL_MSG_BYTES, 4)
        except (TimeoutError, socket.timeout):
            # idle tick: stream the load signal (the handoff picker's feed;
            # only deltas, so an idle worker costs one int compare)
            load = _load()
            if load != last_load:
                last_load = load
                _ctrl_send(ctrl, {"load": load})
            continue
        except OSError:
            data, fds = b"", []
        if not data:
            # supervisor died: a headless worker must not linger holding
            # the port — exit and let clients re-dial whatever replaces us
            _flight.emit(_flight.SHARD_EXIT, 0, shard_id)
            os._exit(0)
        try:
            msg = json.loads(data)
        except ValueError:
            msg = {}
        if msg.get("handoff") and fds:
            for fd in fds:
                try:
                    srv.adopt_socket(socket.socket(
                        socket.AF_INET, socket.SOCK_STREAM, fileno=fd))
                except OSError:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
        elif "peers" in msg:
            from tpurpc.obs import shard as _obs_shard

            _obs_shard.set_peers(
                {int(k): int(v) for k, v in msg["peers"].items()})
        elif "drain" in msg:
            linger = float(msg["drain"])

            def _drain():
                ok = srv.drain(linger)
                _ctrl_send(ctrl, {"drained": shard_id, "clean": bool(ok)})

            threading.Thread(target=_drain, daemon=True,
                             name="tpurpc-shard-drain").start()
        elif "stop" in msg:
            grace = msg.get("stop")
            try:
                srv.stop(grace if isinstance(grace, (int, float)) else None)
            except Exception:
                pass
            _flight.emit(_flight.SHARD_EXIT, 0, shard_id)
            _ctrl_send(ctrl, {"bye": shard_id})
            os._exit(0)


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = ("shard_id", "pid", "ctrl", "alive", "scrape_port",
                 "load", "stopping", "drained")

    def __init__(self, shard_id: int, pid: int, ctrl: socket.socket):
        self.shard_id = shard_id
        self.pid = pid
        self.ctrl = ctrl
        self.alive = True
        self.scrape_port: Optional[int] = None
        self.load = 0
        self.stopping = False
        self.drained = False


class ShardedServer:
    """Supervisor for N per-core worker processes serving ONE port.

    ``build(shard_id) -> Server`` runs IN THE WORKER after the fork: it
    constructs and registers (but does not start) the shard's server —
    handlers, batchers, admission gates, anything. The supervisor itself
    stays thin: bind, fork, broadcast the peer map, monitor, and (handoff
    mode) spread accepted fds.

    Lifecycle: :meth:`start` → traffic → optional :meth:`drain` →
    :meth:`stop`. :meth:`kill_worker` is the chaos-test face (SIGKILL one
    shard; survivors keep serving and the aggregated scrape drops the dead
    shard's series).
    """

    def __init__(self, build: Callable[[int], object], workers: int = 2,
                 address: str = "127.0.0.1:0", *,
                 listener: str = "reuseport",
                 handoff_policy: str = "round_robin"):
        if listener not in ("reuseport", "handoff"):
            raise ValueError(f"unknown listener mode {listener!r}")
        if handoff_policy not in ("round_robin", "least_loaded"):
            raise ValueError(f"unknown handoff policy {handoff_policy!r}")
        self.build = build
        self.n_workers = max(1, int(workers))
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self._want_port = int(port)
        self.listener = listener
        self.handoff_policy = handoff_policy
        self.port: Optional[int] = None
        self._workers: List[_Worker] = []
        self._lock = make_lock("ShardedServer._lock")
        self._stopping = False
        self._started = False
        self._reserve: Optional[socket.socket] = None
        self._listen: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._rr = itertools.count()
        self._fatal: Optional[str] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self, ready_timeout: float = 60.0) -> "ShardedServer":
        if self._started:
            return self
        self._started = True
        if self.listener == "reuseport":
            # reserve the port number before forking: a bound-not-listening
            # REUSEPORT socket pins the port (the kernel only routes among
            # LISTENING sockets, so it never receives a connection)
            self._reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._reserve.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEPORT, 1)
            self._reserve.bind((self.host, self._want_port))
            self.port = self._reserve.getsockname()[1]
        else:
            self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listen.bind((self.host, self._want_port))
            self._listen.listen(128)
            self.port = self._listen.getsockname()[1]
        for i in range(self.n_workers):
            self._spawn(i)
        atexit.register(self._atexit_kill)
        self._await_ready(ready_timeout)
        self._broadcast_peers()
        if self.listener == "handoff":
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True,
                name="tpurpc-shard-accept")
            self._accept_thread.start()
        return self

    def _spawn(self, shard_id: int) -> None:
        # SEQPACKET: every control message (and every SCM_RIGHTS handoff)
        # arrives whole — no framing layer, no fd/payload pairing races
        parent_end, child_end = socket.socketpair(socket.AF_UNIX,
                                                  socket.SOCK_SEQPACKET)
        sys.stdout.flush()
        sys.stderr.flush()
        pid = os.fork()
        if pid == 0:
            # -- child: never returns, never runs the parent's atexit --
            try:
                parent_end.close()
                for s in (self._reserve, self._listen):
                    if s is not None:
                        s.close()
                for w in self._workers:  # siblings' control fds
                    try:
                        w.ctrl.close()
                    except OSError:
                        pass
                _worker_main(child_end, shard_id, self.n_workers, self.build,
                             self.listener, self.host, self.port)
            except BaseException:
                pass
            finally:
                os._exit(1)
        child_end.close()
        w = _Worker(shard_id, pid, parent_end)
        with self._lock:
            self._workers.append(w)
        threading.Thread(target=self._monitor, args=(w,), daemon=True,
                         name=f"tpurpc-shard-mon-{shard_id}").start()

    def _await_ready(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._fatal is not None:
                self.stop()
                raise RuntimeError(f"shard worker failed: {self._fatal}")
            with self._lock:
                ready = [w for w in self._workers
                         if w.scrape_port is not None]
                if len(ready) == self.n_workers:
                    return
            time.sleep(0.01)
        self.stop()
        raise TimeoutError("shard workers did not report ready")

    def _monitor(self, w: _Worker) -> None:
        """One blocking reader per worker control socket: loads, acks, and
        — on EOF — the death path."""
        while True:
            try:
                data = w.ctrl.recv(_CTRL_MSG_BYTES)
            except OSError:
                data = b""
            if not data:
                break
            try:
                msg = json.loads(data)
            except ValueError:
                continue
            if "ready" in msg:
                w.scrape_port = int(msg["scrape_port"])
            elif "load" in msg:
                w.load = int(msg["load"])
            elif "fatal" in msg:
                self._fatal = str(msg["fatal"])
            elif "drained" in msg:
                w.drained = True
            # "bye" needs no action: the stop() path reaps by pid
        self._reap(w)

    def _reap(self, w: _Worker) -> None:
        status = 0
        try:
            _pid, status = os.waitpid(w.pid, 0)
        except ChildProcessError:
            pass
        died = False
        with self._lock:
            if w.alive:
                w.alive = False
                died = not w.stopping and not self._stopping
        if died:
            # tpurpc-manycore death contract: the shard's connections are
            # gone (clients got UNAVAILABLE and re-dial onto live shards —
            # in reuseport mode the kernel stopped routing to the closed
            # socket the instant the process died); telemetry-wise the
            # shard must DROP OUT, so survivors get a peer map without it.
            _flight.emit(_flight.SHARD_DEATH, _SUP_TAG, w.shard_id, status)
            trace_shard.log("shard %d died (status %d)", w.shard_id, status)
            self._broadcast_peers()

    # -- peer map -------------------------------------------------------------

    def scrape_ports(self) -> Dict[int, int]:
        with self._lock:
            return {w.shard_id: w.scrape_port for w in self._workers
                    if w.alive and w.scrape_port is not None}

    def _broadcast_peers(self) -> None:
        peers = self.scrape_ports()
        payload = {"peers": peers}
        with self._lock:
            targets = [w for w in self._workers if w.alive]
        for w in targets:
            _ctrl_send(w.ctrl, payload)

    # -- handoff accept spread ------------------------------------------------

    def _pick_worker(self) -> Optional[_Worker]:
        with self._lock:
            alive = [w for w in self._workers if w.alive]
        if not alive:
            return None
        if self.handoff_policy == "least_loaded":
            # PR 6 load signals, streamed over the control channel: place
            # the connection where the least work is queued (ties rotate)
            best = min(w.load for w in alive)
            alive = [w for w in alive if w.load == best]
        return alive[next(self._rr) % len(alive)]

    def _accept_loop(self) -> None:
        self._listen.settimeout(0.2)
        while not self._stopping:
            try:
                sock, _addr = self._listen.accept()
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                if self._stopping:
                    return
                time.sleep(0.05)
                continue
            handed = False
            for _attempt in range(self.n_workers):
                w = self._pick_worker()
                if w is None:
                    break
                try:
                    socket.send_fds(w.ctrl, [b'{"handoff": 1}'],
                                    [sock.fileno()])
                    _flight.emit(_flight.CONN_HANDOFF, _SUP_TAG, w.shard_id)
                    handed = True
                    break
                except OSError:
                    continue  # racing a worker death: try another
            sock.close()  # worker holds its own duplicate (or nobody: RST)
            if not handed:
                trace_shard.log("handoff: no live worker for connection")

    # -- operator face --------------------------------------------------------

    def alive_workers(self) -> List[int]:
        with self._lock:
            return [w.shard_id for w in self._workers if w.alive]

    def worker_pid(self, shard_id: int) -> Optional[int]:
        with self._lock:
            for w in self._workers:
                if w.shard_id == shard_id:
                    return w.pid
        return None

    def kill_worker(self, shard_id: int, sig: int = signal.SIGKILL) -> bool:
        """Chaos face: kill one shard. Returns False if it wasn't running."""
        with self._lock:
            target = next((w for w in self._workers
                           if w.shard_id == shard_id and w.alive), None)
        if target is None:
            return False
        try:
            os.kill(target.pid, sig)
        except ProcessLookupError:
            return False
        return True

    def drain(self, linger: float = 5.0) -> None:
        """Broadcast a graceful drain (PR 6 semantics, per worker)."""
        with self._lock:
            targets = [w for w in self._workers if w.alive]
        for w in targets:
            _ctrl_send(w.ctrl, {"drain": linger})

    def stop(self, grace: Optional[float] = None,
             timeout: float = 10.0) -> None:
        self._stopping = True
        with self._lock:
            targets = list(self._workers)
        for w in targets:
            w.stopping = True
            _ctrl_send(w.ctrl, {"stop": grace})
        deadline = time.monotonic() + timeout
        for w in targets:
            while w.alive and time.monotonic() < deadline:
                time.sleep(0.01)
            if w.alive:
                try:
                    os.kill(w.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            try:
                w.ctrl.close()
            except OSError:
                pass
        for s in (self._reserve, self._listen):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._reserve = self._listen = None

    def _atexit_kill(self) -> None:
        """Last-resort reaper: a crashed test/supervisor must not leak
        worker processes holding the port."""
        with self._lock:
            targets = [w for w in self._workers if w.alive]
        for w in targets:
            try:
                os.kill(w.pid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
