"""Server: listener → per-connection demux → handler dispatch on a thread pool.

Reference mapping:

* ``Server`` ≈ ``grpc_server`` (``src/core/lib/surface/server.cc``) + C++
  ``ServerBuilder`` (``src/cpp/server/server_builder.cc``): ports, registered
  methods, a thread pool standing in for the CQ/thread-manager machinery
  (``src/cpp/thread_manager/``).
* ``_ServerConnection`` ≈ one accepted chttp2 transport
  (``grpc_server_setup_transport``); its reader thread plays the role of the
  transport's read_action + stream demux.
* ``ServerContext`` mirrors grpcio's (``src/python/grpcio/grpc/_server.py``):
  invocation metadata, deadline, cancellation, ``abort``, trailing metadata.
* Method handlers reuse grpcio's four-shape taxonomy so generated service glue
  ports directly.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import (Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

from tpurpc.core import ctrlring as _ctrl
from tpurpc.core import rendezvous as _rdv
from tpurpc.core.endpoint import (Endpoint, EndpointError, EndpointListener,
                                  passthru_endpoint_pair)
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _obs_metrics
from tpurpc.obs import profiler as _obs_profiler
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc import frame as fr
from tpurpc.rpc.status import (AbortError, Deserializer, Metadata, Serializer,
                               StatusCode, deserialize as _deserialize,
                               identity_codec as _identity)
from tpurpc.utils.config import get_config
from tpurpc.utils.trace import TraceFlag

trace_server = TraceFlag("server")
_log = logging.getLogger("tpurpc.server")

# tpurpc-lens (ISSUE 8) sampling-profiler frame markers: handler dispatch
# on either execution path is the `dispatch` stage
_LENS_STAGES = {
    "_run_handler": "dispatch",
    "_run_handler_inner": "dispatch",
    "_run_inline": "dispatch",
}
_obs_profiler.register_stages(__file__, _LENS_STAGES)

#: tpurpc-scope (ISSUE 4): always-on server-side handler latency (one
#: perf_counter pair + one amortized histogram record per RPC — what
#: `tools.top` renders as serving percentiles)
_SRV_CALL_US = _obs_metrics.histogram("srv_call_us", kind="latency")
#: tpurpc-blackbox (ISSUE 5): per-method, per-status-code RED counters
#: (`srv_calls{method,code}` on /metrics); shared with the h2 plane
_SRV_CALLS = _obs_metrics.labeled_counter("srv_calls", ("method", "code"))
#: tpurpc-fleet (ISSUE 6): admission-control shed counter + the interned
#: flight tags for the emission sites below (pure-int plumbing — the
#: `flight` lint rule covers this module)
_SRV_SHED = _obs_metrics.counter("srv_admission_rejected")
_SRV_INLINE_TAG = _flight.tag_for("srv-inline")
_SRV_ADMIT_TAG = _flight.tag_for("srv-admission")
_SRV_DRAIN_TAG = _flight.tag_for("srv-drain")

#: trailing-metadata key carrying the ORCA-style per-response load report
#: (``"<inflight>,<queue_depth>,<p99_ms>"`` — see Server._load_md); the
#: client channel strips it and feeds the ``least_loaded`` LB policy
LOAD_KEY = "tpurpc-load"
#: trailing-metadata key on admission rejections: how long the client
#: should back off before retrying (milliseconds; RetryPolicy honors it)
PUSHBACK_KEY = "tpurpc-pushback-ms"


class AdmissionGate:
    """Server-side overload admission control (tpurpc-fleet, ISSUE 6).

    The gate sits at stream admission — BEFORE handler lookup, context
    construction, or any pool handoff — and sheds load while the server
    can still say so cheaply, instead of queueing toward collapse
    (RDMAvisor's shared-daemon lesson: a multiplexing service must bound
    what it accepts, arXiv:1802.01870). Two signals:

    * **queue depth** — admitted-but-unfinished RPCs. Below
      ``soft_limit`` everything is admitted; at ``max_inflight`` nothing
      is.
    * **rolling latency** — between the two limits, admission requires
      the stall watchdog's rolling p99 (PR 5's per-method duration
      windows) to be under ``latency_slo_ms``: rising latency at partial
      queue depth is the pre-collapse signature the hard limit alone
      would miss.

    Rejections carry ``UNAVAILABLE`` plus :data:`PUSHBACK_KEY` trailing
    metadata whose value grows with the excess — clients with a
    :class:`~tpurpc.rpc.channel.RetryPolicy` honor it as their backoff
    floor, so a shedding server is not immediately re-hammered. Health
    RPCs are exempt (the server dispatch layer skips the gate for
    ``/grpc.health.``-prefixed paths): an overloaded-but-alive backend
    must keep answering its probes.
    """

    def __init__(self, max_inflight: int, *,
                 soft_limit: Optional[int] = None,
                 latency_slo_ms: Optional[float] = None,
                 latency_ms_fn: "Optional[Callable[[], Optional[float]]]"
                 = None,
                 base_pushback_ms: int = 25,
                 max_pushback_ms: int = 1000):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = int(max_inflight)
        self.soft_limit = (int(soft_limit) if soft_limit is not None
                           else max(1, (self.max_inflight * 3) // 4))
        if not 1 <= self.soft_limit <= self.max_inflight:
            raise ValueError("need 1 <= soft_limit <= max_inflight")
        self.latency_slo_ms = latency_slo_ms
        #: tpurpc-cadence (ISSUE 10): a workload-specific latency signal
        #: replacing the watchdog's RPC-level rolling p99. A decode server
        #: hands the scheduler's step-time p99 here: generate streams are
        #: SUPPOSED to be long-lived, so their RPC duration says nothing,
        #: while a rising step time is exactly the pre-collapse signature
        #: the between-limits band exists to catch. Returns ms or None
        #: (no signal yet = not slow).
        self.latency_ms_fn = latency_ms_fn
        self.base_pushback_ms = int(base_pushback_ms)
        self.max_pushback_ms = int(max_pushback_ms)
        self._inflight = 0
        self._lock = threading.Lock()
        self.rejected = 0

    def _latency_ms(self) -> "Optional[float]":
        if self.latency_ms_fn is not None:
            try:
                return self.latency_ms_fn()
            except Exception:
                return None  # a broken probe never blocks admission
        from tpurpc.obs import watchdog as _watchdog

        p99 = _watchdog.get().rolling_p99_ns()
        return None if p99 is None else p99 / 1e6

    def try_admit(self) -> Optional[int]:
        """None = admitted (the caller OWES a :meth:`release`); an int =
        rejected, with that many milliseconds of retry pushback."""
        with self._lock:
            n = self._inflight
            if n < self.soft_limit:
                self._inflight = n + 1
                return None
            slow = False
            if n < self.max_inflight:
                if self.latency_slo_ms is not None:
                    lat = self._latency_ms()
                    slow = (lat is not None
                            and lat > self.latency_slo_ms)
                if not slow:
                    self._inflight = n + 1
                    return None
            self.rejected += 1
            excess = max(1, n - self.soft_limit + 1)
            return min(self.max_pushback_ms,
                       self.base_pushback_ms * excess)

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def connection_pushback_ms(self) -> Optional[int]:
        """Connection-level pressure probe for the accept path (ISSUE 16
        accept-storm hardening — ``EndpointListener`` consults this before
        spending any handshake work on a freshly accepted socket). Sheds
        new CONNECTIONS only at hard saturation (inflight at
        ``max_inflight``): between the limits, existing clients keep
        reconnecting and the per-RPC gate does the fine-grained shedding.
        Pure probe — admits nothing, so no :meth:`release` is owed."""
        with self._lock:
            n = self._inflight
            if n < self.max_inflight:
                return None
            excess = max(1, n - self.soft_limit + 1)
            return min(self.max_pushback_ms,
                       self.base_pushback_ms * excess)

    @classmethod
    def from_env(cls) -> "Optional[AdmissionGate]":
        """Gate configured by ``TPURPC_ADMISSION_MAX_INFLIGHT`` (+ optional
        ``TPURPC_ADMISSION_SLO_MS``), or None when unset — admission
        control is opt-in, like gRPC's resource quota."""
        import os

        raw = os.environ.get("TPURPC_ADMISSION_MAX_INFLIGHT", "")
        if not raw:
            return None
        try:
            max_inflight = int(raw)
        except ValueError:
            return None
        if max_inflight < 1:
            return None
        slo = None
        raw_slo = os.environ.get("TPURPC_ADMISSION_SLO_MS", "")
        if raw_slo:
            try:
                slo = float(raw_slo)
            except ValueError:
                slo = None
        return cls(max_inflight, latency_slo_ms=slo)


def _extract_trace(metadata) -> "Optional[_tracing.TraceContext]":
    """The tpurpc-trace context a client attached (sampled or tail-
    provisional), stripped from ``metadata`` IN PLACE — the context is
    transport-internal and must not surface to handlers (grpcio parity
    with te/content-type filtering; with tail capture on, EVERY call
    carries it)."""
    if not _tracing.LIVE:
        return None
    for i, (key, value) in enumerate(metadata):
        if key == _tracing.HEADER:
            del metadata[i]
            return _tracing.adopt(value)
    return None


class RpcMethodHandler:
    """One registered method: shape + behavior + codecs (grpcio taxonomy).

    ``inline=True`` (unary_unary only) runs the handler ON THE CONNECTION
    READER THREAD when the request completes — no thread-pool handoff, the
    low-latency reactor path (the native callback API's contract,
    ``native/include/tpurpc/server.h``; gRPC's inlineable callback methods
    are the upstream analog). The handler MUST NOT block: it stalls every
    stream on its connection.
    """

    __slots__ = ("kind", "behavior", "request_deserializer",
                 "response_serializer", "inline")

    KINDS = ("unary_unary", "unary_stream", "stream_unary", "stream_stream")

    def __init__(self, kind: str, behavior: Callable,
                 request_deserializer: Deserializer = _identity,
                 response_serializer: Serializer = _identity,
                 inline: bool = False):
        if kind not in self.KINDS:
            raise ValueError(f"bad handler kind {kind}")
        if inline and kind != "unary_unary":
            raise ValueError("inline handlers are unary_unary only")
        self.inline = inline
        self.kind = kind
        self.behavior = behavior
        self.request_deserializer = request_deserializer
        self.response_serializer = response_serializer

    @property
    def request_streaming(self) -> bool:
        return self.kind.startswith("stream")

    @property
    def response_streaming(self) -> bool:
        return self.kind.endswith("stream")


def unary_unary_rpc_method_handler(behavior, request_deserializer=_identity,
                                   response_serializer=_identity,
                                   inline: bool = False):
    return RpcMethodHandler("unary_unary", behavior, request_deserializer,
                            response_serializer, inline=inline)


def unary_stream_rpc_method_handler(behavior, request_deserializer=_identity,
                                    response_serializer=_identity):
    return RpcMethodHandler("unary_stream", behavior, request_deserializer,
                            response_serializer)


def stream_unary_rpc_method_handler(behavior, request_deserializer=_identity,
                                    response_serializer=_identity):
    return RpcMethodHandler("stream_unary", behavior, request_deserializer,
                            response_serializer)


def stream_stream_rpc_method_handler(behavior, request_deserializer=_identity,
                                     response_serializer=_identity):
    return RpcMethodHandler("stream_stream", behavior, request_deserializer,
                            response_serializer)


def method_handlers_generic_handler(service: str,
                                    method_handlers: Dict[str, RpcMethodHandler]):
    """grpcio-shaped: returns {path: handler} for Server.add_generic_handlers."""
    return {f"/{service}/{name}": h for name, h in method_handlers.items()}


class _HandlerCallDetails:
    """grpc.HandlerCallDetails shape for GenericRpcHandler.service()."""

    __slots__ = ("method", "invocation_metadata")

    def __init__(self, method: str, invocation_metadata=()):
        self.method = method
        self.invocation_metadata = tuple(invocation_metadata or ())


class ServerContext:
    """Handed to every handler; grpcio-compatible surface."""

    def __init__(self, conn: "_ServerConnection", stream: "_ServerStream",
                 metadata: List[Tuple[str, "str | bytes"]],
                 deadline: Optional[float]):
        self._conn = conn
        self._stream = stream
        self._metadata = metadata
        self._deadline = deadline
        self._trailing: Metadata = ()
        self._initial_sent = False
        self._cancelled = threading.Event()
        self._code: Optional[StatusCode] = None
        self._details = ""

    # grpcio surface ---------------------------------------------------------

    def invocation_metadata(self) -> Metadata:
        return list(self._metadata)

    def peer(self) -> str:
        return self._conn.endpoint.peer

    def auth_context(self) -> dict:
        """grpcio's ServerContext.auth_context: {} on plaintext,
        transport_security_type alone on certless TLS, plus the peer's
        x509 names under mTLS. Probed through the Endpoint seam (ring
        platforms keep the TLS socket as the pair's notify channel), and
        computed once per context — the cert can't change mid-call."""
        cached = getattr(self, "_auth_ctx", None)
        if cached is not None:
            return cached
        cert = self._conn.endpoint.peer_cert()
        if cert is None:  # non-TLS transport
            out: dict = {}
        elif not cert:  # TLS without a client certificate
            out = {"transport_security_type": [b"ssl"]}
        else:
            out = {"transport_security_type": [b"ssl"]}
            # every SAN kind counts as identity (URI carries SPIFFE ids)
            sans = [v.encode() if isinstance(v, str) else str(v).encode()
                    for _kind, v in cert.get("subjectAltName", ())]
            if sans:
                out["x509_subject_alternative_name"] = sans
            for rdn in cert.get("subject", ()):
                for key, val in rdn:
                    if key == "commonName":
                        out.setdefault("x509_common_name", []).append(
                            val.encode())
        self._auth_ctx = out
        return out

    def peer_identity_key(self) -> "Optional[str]":
        ac = self.auth_context()
        for key in ("x509_subject_alternative_name", "x509_common_name"):
            if key in ac:
                return key
        return None

    def peer_identities(self):
        key = self.peer_identity_key()
        return self.auth_context()[key] if key else None

    @property
    def device_ring(self):
        """The connection's device (HBM) receive ring, or None off-platform.

        Present only when the transport is a
        :class:`tpurpc.tpu.endpoint.TpuRingEndpoint`
        (``GRPC_PLATFORM_TYPE=TPU``); tensor handlers registered with
        ``device=True`` decode through it."""
        from tpurpc.core.endpoint import device_ring_of

        return device_ring_of(self._conn.endpoint)

    def deadline_remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    time_remaining = deadline_remaining

    def is_active(self) -> bool:
        return not self._cancelled.is_set()

    def cancel(self) -> None:
        self._cancelled.set()

    def set_trailing_metadata(self, metadata: Metadata) -> None:
        self._trailing = metadata

    def set_code(self, code: StatusCode) -> None:
        self._code = code

    def set_details(self, details: str) -> None:
        self._details = details

    def abort(self, code: StatusCode, details: str = ""):
        if code is StatusCode.OK:
            raise ValueError("abort with OK is invalid")
        raise AbortError(code, details)

    def send_initial_metadata(self, metadata: Metadata) -> None:
        if self._initial_sent:
            raise RuntimeError("initial metadata already sent")
        self._initial_sent = True
        self._conn.writer.send(fr.HEADERS, 0, self._stream.stream_id,
                               fr.encode_metadata(list(metadata)))

    # internal ---------------------------------------------------------------

    def _deadline_exceeded(self) -> bool:
        return self._deadline is not None and time.monotonic() >= self._deadline


class _ServerStream:
    """Inbound half of one RPC: request frames → handler-visible iterator."""

    _END = object()
    _OVERSIZED = object()
    _BAD_COMPRESSION = object()

    def __init__(self, stream_id: int, queue_depth: int = 64,
                 recv_limit: Optional[int] = None):
        self.stream_id = stream_id
        #: the EFFECTIVE receive bound (server override or config), quoted in
        #: the RESOURCE_EXHAUSTED details so operators debug the right knob
        self.recv_limit = recv_limit
        self.requests: "queue.Queue[object]" = queue.Queue()
        #: fragment assembly — the FrameReader sink appends wire bytes here
        self.assembly = fr.Assembly()
        self.half_closed = False
        #: a request arrived FLAG_COMPRESSED: mirror the encoding on
        #: responses (the peer demonstrably speaks it)
        self.peer_compressed = False
        self.context: Optional[ServerContext] = None
        #: tpurpc-scope: the caller's trace context (None untraced) + the
        #: HEADERS-arrival stamp feeding the "dispatch" span
        self.trace_ctx = None
        self.trace_t0 = 0
        #: tpurpc-blackbox: the status this stream terminated with (set at
        #: every trailer-send site) — what srv_calls{method,code} records
        self.final_code: Optional[StatusCode] = None
        #: reactor-path pending invocation: (handler, ctx, path) set by
        #: _start_stream for inline unary handlers; consumed by the sink's
        #: commit when the request completes (runs on the reader thread)
        self.inline_call = None
        self.inline_timer = None  # deadline watchdog for the parked call
        #: Backpressure: at most queue_depth completed-but-unconsumed
        #: messages per stream. The connection READER blocks acquiring a
        #: credit, which stops draining the transport, which dries the
        #: ring's credits, which stalls the sender — memory stays bounded
        #: end to end. Control sentinels (_END/_OVERSIZED) bypass: they must
        #: never deadlock delivery. (resource_quota.cc's role, per-stream.)
        self._credits = threading.BoundedSemaphore(max(1, queue_depth))

    def _acquire_credit(self) -> bool:
        """Block until a queue slot frees; False if the stream/ctx died
        meanwhile (drop the message — nobody will read it)."""
        while not self._credits.acquire(timeout=0.25):
            ctx = self.context
            if ctx is not None and not ctx.is_active():
                return False
        return True

    def _release_credit(self) -> None:
        try:
            self._credits.release()
        except ValueError:
            pass  # sentinel consumption paths may over-release; cap holds

    def commit_message(self, more: bool, end_stream: bool,
                       no_message: bool = False,
                       oversized: bool = False,
                       compressed: bool = False) -> None:
        if oversized and not more:
            self.assembly.oversized = False
            self.requests.put(self._OVERSIZED)
        elif not no_message and not more:
            # take() detaches the storage (consumers may alias it); the
            # Assembly object itself is reusable for the next message.
            if self._acquire_credit():
                body = self.assembly.take()
                if compressed:
                    self.peer_compressed = True
                    try:
                        # limit on the POST-decompression size (bomb guard)
                        body = fr.decompress_message(body, self.recv_limit)
                    except fr.DecompressTooLarge:
                        self._release_credit()  # sentinels bypass credits
                        self.requests.put(self._OVERSIZED)
                        body = None
                    except fr.FrameError:
                        self._release_credit()
                        self.requests.put(self._BAD_COMPRESSION)
                        body = None
                if body is not None:
                    self.requests.put(body)
            else:
                self.assembly.take()  # stream dead: drop, free the bytes
        if end_stream:
            self.half_closed = True
            self.requests.put(self._END)

    def commit_external(self, body, end_stream: bool) -> None:
        """tpurpc-express: a rendezvous'd request payload — already whole,
        already in its final landing buffer (decode aliases it in place).
        Same per-stream credit backpressure as framed commits."""
        if self._acquire_credit():
            self.requests.put(body)
        if end_stream:
            self.half_closed = True
            self.requests.put(self._END)

    def cancel(self) -> None:
        if self.context is not None:
            self.context.cancel()
        self.requests.put(self._END)

    def next_request(self, timeout: Optional[float] = None):
        """One queue item with its credit returned; queue.Empty on timeout."""
        item = self.requests.get(timeout=timeout)
        if item not in (self._END, self._OVERSIZED, self._BAD_COMPRESSION):
            self._release_credit()
        return item

    def request_iterator(self, deserializer: Deserializer,
                         context: ServerContext) -> Iterator[object]:
        while True:
            item = self.next_request()
            if item is self._END:
                return
            if item is self._OVERSIZED:
                raise AbortError(
                    StatusCode.RESOURCE_EXHAUSTED,
                    "received message larger than max "
                    f"({self.recv_limit} bytes)")
            if item is self._BAD_COMPRESSION:
                raise AbortError(StatusCode.INTERNAL,
                                 "compressed message failed to decompress")
            if not context.is_active():
                return
            yield _deserialize(deserializer, item)


class _ServerSink(fr.MessageSink):
    """Routes request MESSAGE bytes into per-stream assembly buffers."""

    def __init__(self, conn: "_ServerConnection"):
        self._conn = conn
        self._discard = fr.Assembly()

    def buffer_for(self, stream_id: int) -> fr.Assembly:
        with self._conn._lock:
            st = self._conn._streams.get(stream_id)
        if st is None:
            self._discard.take()  # drop late bytes
            return self._discard
        return st.assembly

    def commit(self, stream_id: int, flags: int) -> None:
        with self._conn._lock:
            st = self._conn._streams.get(stream_id)
        if st is not None:
            st.commit_message(bool(flags & fr.FLAG_MORE),
                              bool(flags & fr.FLAG_END_STREAM),
                              bool(flags & fr.FLAG_NO_MESSAGE),
                              oversized=st.assembly.oversized,
                              compressed=bool(flags & fr.FLAG_COMPRESSED))
            if flags & fr.FLAG_END_STREAM:
                ic = self._conn._claim_inline(st)
                if ic is not None:
                    # reactor path: the whole request is in st.requests —
                    # run the handler ON THE READER THREAD (no pool
                    # handoff). The native callback API's exact contract
                    # (server.h), opt-in per handler; a blocking handler
                    # stalls this connection.
                    handler, ctx, path = ic
                    self._conn._run_inline(handler, st, ctx, path)


#: reentrancy guard for the inline dispatch path: set while a thread is
#: inside an inline handler. An inline handler that (transitively) completes
#: ANOTHER request on the same thread — inproc passthru endpoints and
#: loopback self-calls can do this synchronously — must not nest dispatches:
#: unbounded recursion, and a second handler's blocking would be invisible
#: to the first connection. Nested inline work reroutes to the pool.
_inline_tls = threading.local()


class _ServerConnection:
    def __init__(self, server: "Server", endpoint: Endpoint,
                 preface_consumed: bool = False):
        self.server = server
        self.endpoint = endpoint
        # coalesce=True: unary responses completing close together on this
        # connection (any mix of pool and inline handlers) flush as one
        # gathered writev — one client-side wakeup for N streams (ISSUE 3)
        self.writer = fr.FrameWriter(endpoint, coalesce=True)
        self.reader = fr.FrameReader(endpoint,
                                     expect_preface=not preface_consumed)
        self.reader.sink = _ServerSink(self)
        self.reader.sink.max_message_bytes = server.max_receive_message_length
        self._streams: Dict[int, _ServerStream] = {}
        self._lock = threading.Lock()
        self.alive = True
        self.draining = False  # GOAWAY sent; no new streams accepted
        self.streams_started = 0  # channelz SocketData counter
        self.last_frame = time.monotonic()  # any inbound frame refreshes
        # tpurpc-express: the rendezvous link (big requests land one-sided
        # in this side's pool; big responses go one-sided into the
        # client's). Created BEFORE the reader starts so the client's
        # capability hello can never race past an unarmed link.
        self.rdv = _rdv.link_for_endpoint(
            endpoint, "srv:" + getattr(endpoint, "peer", "?"),
            self._rdv_send_op, self._rdv_deliver,
            send_ops=self._rdv_send_ops)
        self.writer.rdv = self.rdv
        # tpurpc-pulse (ISSUE 13): the descriptor-ring control plane —
        # our receive ring rides the hello blob; the peer's arrives in its
        # hello and moves this link's control ops off frames entirely
        self._frames_dispatched = 0
        self.ctrl = None
        if self.rdv is not None and _ctrl.enabled():
            try:
                self.ctrl = _ctrl.CtrlPlane(
                    "srv:" + getattr(endpoint, "peer", "?"))
            except Exception:
                self.ctrl = None  # no shm: framed control forever
            if self.ctrl is not None:
                self.rdv.ctrl_post = self._rdv_ctrl_post
                self.rdv.ctrl_drain = self._ctrl_drain
                # per-stream order across the ring/framed split: control
                # ops posted before a sink-routed MESSAGE deliver first
                self.reader.pre_commit = self._ctrl_drain
        if self.rdv is not None:
            self.rdv.recv_limit = server.max_receive_message_length
            # ring planes negotiated at the pair bootstrap (Address.caps)
            pair = getattr(endpoint, "pair", None)
            if pair is not None and "rdv" in getattr(pair, "peer_caps",
                                                     ()):
                self.rdv.on_peer_hello()
            hello = _rdv.HELLO_PAYLOAD
            if self.ctrl is not None:
                hello += self.ctrl.hello_blob()
            try:
                self.writer.send(fr.PING, 0, 0, hello)
            except (EndpointError, OSError, fr.FrameError):
                pass  # connection dying; the read loop surfaces it
        self._thread = threading.Thread(target=self._read_loop, daemon=True,
                                        name="tpurpc-srv-reader")
        self._thread.start()
        self._start_age_timer()
        self._start_keepalive()

    def _start_keepalive(self) -> None:
        """Server-side keepalive (the same GRPC_ARG_KEEPALIVE_TIME_MS knob,
        symmetric with the client's): PING a quiet client, close the
        connection when nothing — not even the PONG — arrives within the
        timeout. Dead clients otherwise pin pooled pairs/rings forever."""
        cfg = get_config()
        if cfg.keepalive_time_ms <= 0:
            return
        interval = cfg.keepalive_time_ms / 1000.0
        timeout = max(0.001, cfg.keepalive_timeout_ms / 1000.0)
        from tpurpc.utils.timers import schedule

        from tpurpc.utils.timers import run_blocking

        state = {"ping_sent_at": None}  # monotonic ts of outstanding PING

        def tick():
            # Wheel-scheduled (no thread per connection; iomgr-timer style).
            if not self.alive:
                return
            with self._lock:
                busy = bool(self._streams)
            if busy:
                # In-flight streams: the reader may be deliberately
                # stalled on per-stream backpressure (stream_queue_depth)
                # with the client's PONGs sitting unread — reaping here
                # would kill live transfers. Peer death mid-stream is
                # caught by write errors / EOF; keepalive exists for the
                # IDLE-and-silent case (dead clients pinning pool state).
                state["ping_sent_at"] = None
                self._ka_handle = schedule(min(interval, 1.0), tick)
                return
            ping_sent_at = state["ping_sent_at"]
            if ping_sent_at is not None and self.last_frame >= ping_sent_at:
                ping_sent_at = state["ping_sent_at"] = None  # PING answered
            quiet = time.monotonic() - self.last_frame
            if quiet < interval:
                state["ping_sent_at"] = None  # frames flowed; window restarts
                self._ka_handle = schedule(min(interval - quiet, 1.0), tick)
                return
            if ping_sent_at is None:
                # Stamp BEFORE the send: on one core the reader can process
                # the loopback PONG before a stamp-after-send executes, and
                # the answered-check would then read the PING as ignored —
                # a healthy-but-quiet client reaped at the next tick.
                state["ping_sent_at"] = time.monotonic()

                def send_ping():  # endpoint write: never on the wheel
                    try:  # ONE ping per silence window (gRPC parity)
                        self.writer.send(fr.PING, 0, 0, b"srv-keepalive")
                    except (EndpointError, OSError, fr.FrameError):
                        self._shutdown()

                run_blocking(send_ping)
                self._ka_handle = schedule(min(timeout, 1.0), tick)
                return
            if time.monotonic() - ping_sent_at >= timeout:
                trace_server.log("keepalive: client silent %.1fs, closing",
                                 quiet)
                run_blocking(self._shutdown)
                return
            self._ka_handle = schedule(min(timeout, 1.0), tick)

        self._ka_handle = schedule(min(interval, 1.0), tick)

    def _start_age_timer(self) -> None:
        """max_age filter analog (GRPC_ARG_MAX_CONNECTION_AGE_MS, off by
        default): after the age, GOAWAY the client — it stops opening
        streams here and dials fresh — then close once in-flight streams
        drain. Bounds how long one connection monopolizes pooled pairs."""
        age_ms = get_config().max_connection_age_ms
        if age_ms <= 0:
            return

        def expire():
            with self._lock:
                if not self.alive or self.draining:
                    return
                self.draining = True
                empty = not self._streams
            try:
                self.writer.send(fr.GOAWAY, 0, 0, b"max_connection_age")
            except (EndpointError, OSError, fr.FrameError):
                return  # connection already dying
            if empty:
                self._linger_then_shutdown()

        from tpurpc.utils.timers import run_blocking, schedule

        # the GOAWAY is an endpoint write (can stall on a credit-wedged
        # transport): run it off the wheel thread
        self._age_timer = schedule(age_ms / 1000.0,
                                   lambda: run_blocking(expire))

    #: After GOAWAY, wait this long before closing the socket: a HEADERS
    #: frame already in flight from a client that hasn't processed the
    #: GOAWAY yet must be answered with RST "connection draining" (which
    #: clients retry transparently) — closing instantly turns that race
    #: into a visible UNAVAILABLE "server closed connection".
    _GOAWAY_LINGER_S = 1.0

    def _linger_then_shutdown(self) -> None:
        from tpurpc.utils.timers import run_blocking, schedule

        self._linger_timer = schedule(
            self._GOAWAY_LINGER_S, lambda: run_blocking(self._shutdown))

    def _read_loop(self) -> None:
        if self.rdv is not None:
            # a handler sending a big response on THIS thread (inline
            # dispatch) must never park waiting for a CLAIM this very
            # thread would have to deliver — such sends stay framed
            self.rdv.disallowed_thread = threading.get_ident()
        try:
            while True:
                f = self._read_frame_ctrl()
                if f is None:
                    break
                self.last_frame = time.monotonic()  # client is alive
                if f is fr.CONSUMED:  # MESSAGE already routed via the sink
                    self._frames_dispatched += 1
                    continue
                self._dispatch(f)
                self._frames_dispatched += 1
        except (EndpointError, fr.FrameError, OSError) as exc:
            trace_server.log("server connection error: %s", exc)
        finally:
            self._shutdown()

    # -- rendezvous plumbing (tpurpc-express) ---------------------------------

    def _rdv_send_op(self, op: int, stream_id: int, payload: bytes) -> None:
        self.writer.send(fr.RDV_FRAME_OF_OP[op], 0, stream_id, payload)

    def _rdv_send_ops(self, ops) -> None:
        """Cold-path coalescer flush: every queued control op in ONE
        gathered writev (tpurpc-pulse)."""
        self.writer.send_many([(fr.RDV_FRAME_OF_OP[op], 0, sid, payload)
                               for op, sid, payload in ops])

    # -- descriptor-ring control plane (tpurpc-pulse, ISSUE 13) ---------------

    def _rdv_ctrl_post(self, op: int, stream_id: int,
                       payload: bytes) -> bool:
        plane = self.ctrl
        if plane is None:
            return False
        return plane.post(op, stream_id, payload, self.writer.frames_sent,
                          self._ctrl_kick)

    def _ctrl_kick(self) -> None:
        try:
            self.writer.send(fr.CTRL_KICK, 0, 0, b"")
        except (EndpointError, OSError, fr.FrameError):
            pass  # connection dying; the read loop surfaces it

    def _frames_count(self) -> int:
        return self._frames_dispatched

    def _ctrl_drain(self) -> int:
        plane, rdv = self.ctrl, self.rdv
        if plane is None or rdv is None:
            return 0
        n = plane.drain(rdv.on_op, self._frames_count)
        if n:
            # ring records are client-liveness evidence exactly as frames
            # are: a pure-ring steady state must not read as "silent"
            self.last_frame = time.monotonic()
        return n

    def _read_frame_ctrl(self, timeout=None):
        plane = self.ctrl
        if plane is None or plane.rx is None:
            return self.reader.read_frame(timeout=timeout)
        return _ctrl.read_frame_polled(self.reader.read_frame,
                                       self._ctrl_drain, plane, timeout)

    def _rdv_deliver(self, stream_id: int, flags: int, body) -> None:
        """A completed rendezvous request payload: the stream's next
        message, zero-copy (the body aliases the landing region). Mirrors
        _ServerSink.commit — including the reactor claim when the message
        half-closes the stream."""
        with self._lock:
            st = self._streams.get(stream_id)
        if st is None:
            return
        st.commit_external(body, bool(flags & fr.FLAG_END_STREAM))
        if flags & fr.FLAG_END_STREAM:
            ic = self._claim_inline(st)
            if ic is not None:
                handler, ctx, path = ic
                self._run_inline(handler, st, ctx, path)

    def _dispatch(self, f: fr.Frame) -> None:
        if f.type == fr.PING:
            if (self.rdv is not None
                    and f.payload.startswith(_rdv.HELLO_PAYLOAD)):
                self.rdv.on_peer_hello(f.payload)
                if self.ctrl is not None:
                    self.ctrl.on_hello(
                        f.payload[len(_rdv.HELLO_PAYLOAD):])
            self.writer.send(fr.PONG, 0, 0, f.payload)
            return
        if f.type == fr.CTRL_KICK:
            return  # the wake itself was the delivery: the loop drains
        if f.type in fr.RDV_OP_OF_FRAME:
            if self.rdv is not None:
                self.rdv.on_op(fr.RDV_OP_OF_FRAME[f.type], f.stream_id,
                               f.payload)
            return
        if f.type == fr.PONG:
            return
        if f.type == fr.GOAWAY:
            raise EndpointError("client sent GOAWAY")
        with self._lock:
            st = self._streams.get(f.stream_id)
        if f.type == fr.HEADERS:
            if st is not None:
                raise fr.FrameError(f"duplicate HEADERS for stream {f.stream_id}")
            self._start_stream(f)
            return
        if st is None:
            return  # frame for a finished/cancelled stream
        if f.type == fr.MESSAGE:  # only without a sink (never in practice)
            st.assembly.append(f.payload)
            st.commit_message(bool(f.flags & fr.FLAG_MORE),
                              bool(f.flags & fr.FLAG_END_STREAM),
                              bool(f.flags & fr.FLAG_NO_MESSAGE),
                              compressed=bool(f.flags & fr.FLAG_COMPRESSED))
        elif f.type == fr.RST:
            st.cancel()
            self._finish_stream(st)
        else:
            raise fr.FrameError(f"unexpected frame {f!r}")

    def _start_stream(self, f: fr.Frame) -> None:
        path, timeout_us, metadata = fr.parse_headers(f.payload)
        st = _ServerStream(f.stream_id,
                           queue_depth=get_config().stream_queue_depth,
                           recv_limit=self.server.max_receive_message_length)
        #: health probes are admitted during drain and excluded from the
        #: drain's remaining-stream count (a held-open Watch must not make
        #: a clean drain report as missing its budget)
        st.is_probe = path.startswith("/grpc.health.")
        # Health RPCs are admitted even while draining: the drain contract
        # is that the health service ANSWERS NOT_SERVING — a refused probe
        # reads as death, not as leaving rotation.
        probe = st.is_probe
        with self._lock:
            # server._draining closes the adoption race: a connection
            # dialed into a draining server can dispatch HEADERS before
            # _sniff_and_serve marks it draining — the stream must still
            # be refused (zero-failed-RPC drain contract)
            if (self.draining or self.server._draining) and not probe:
                rejected = True  # raced the GOAWAY: client dials fresh
            else:
                rejected = False
                self._streams[f.stream_id] = st
                self.streams_started += 1
        if rejected:
            # FLAG_REFUSED is the contract ("no handler ran, replay is
            # safe"); the detail text is for humans only
            self.writer.send(fr.RST, fr.FLAG_REFUSED, f.stream_id,
                             fr.rst_payload(StatusCode.UNAVAILABLE,
                                            "connection draining (max_age)"))
            return
        # tpurpc-fleet admission control: shed BEFORE any handler work.
        # Health probes are exempt — an overloaded backend must keep
        # answering its LB's probes or shedding reads as death.
        gate = self.server.admission
        if gate is not None and not path.startswith("/grpc.health."):
            pushback_ms = gate.try_admit()
            if pushback_ms is not None:
                _SRV_SHED.inc()
                inflight_now = gate.inflight()
                _flight.emit(_flight.ADMIT_REJECT, _SRV_ADMIT_TAG,
                             inflight_now, pushback_ms)
                self._send_trailers(
                    st, StatusCode.UNAVAILABLE,
                    f"server overloaded: admission rejected "
                    f"({inflight_now} in flight); retry after "
                    f"{pushback_ms}ms",
                    [(PUSHBACK_KEY, str(pushback_ms))])
                self._finish_stream(st)
                return
            st._gate = gate  # released exactly once in _finish_stream
        deadline = (None if timeout_us is None
                    else time.monotonic() + timeout_us / 1e6)
        # tpurpc-scope: pick up a sampled caller's trace context; the
        # HEADERS→handler-start interval becomes the "dispatch" span
        st.trace_ctx = _extract_trace(metadata)
        st.trace_t0 = time.monotonic_ns() if st.trace_ctx is not None else 0
        handler = self.server._lookup_intercepted(path, metadata)
        if handler is None:
            self._send_trailers(st, StatusCode.UNIMPLEMENTED,
                                f"unknown method {path}")
            self._finish_stream(st)
            return
        ctx = ServerContext(self, st, metadata, deadline)
        st.context = ctx
        if getattr(handler, "inline", False):
            # reactor path: defer to the sink's commit (reader thread) when
            # the request message completes — zero pool handoffs. The
            # declared deadline still needs a watchdog: a client that opens
            # the stream but never sends the body would otherwise park the
            # call forever (and a non-empty _streams suppresses the
            # keepalive reaper) — non-inline handlers get this from
            # next_request(timeout=...).
            st.inline_call = (handler, ctx, path)
            if deadline is not None:
                # shared timer wheel, NOT threading.Timer: a thread spawn
                # per call was measured as a 25% RPC-rate regression. The
                # expiry itself sends trailers (endpoint write) — off-wheel.
                from tpurpc.utils.timers import run_blocking, schedule

                st.inline_timer = schedule(
                    max(0.0, deadline - time.monotonic()),
                    lambda: run_blocking(lambda: self._inline_deadline(st)))
            return
        try:
            self.server._pool.submit(self._run_handler, handler, st, ctx, path)
        except RuntimeError:  # pool shut down: server is stopping
            self._send_trailers(st, StatusCode.UNAVAILABLE, "server shutting down")
            self._finish_stream(st)
            # A server that cannot run handlers must not keep answering: kill
            # the connection so the client's subchannel redials (a fresh
            # server may own this port by now). Without this, a connection
            # adopted in the stop() race answers every call with this trailer
            # forever and the client — seeing healthy RPC replies — never
            # reconnects (observed: 597 failed attempts/60s in round-2 CI).
            self.close()

    def _claim_inline(self, st: _ServerStream):
        """Atomically take a parked inline call (the sink's commit and the
        deadline watchdog race for it; exactly one side runs)."""
        with self._lock:
            ic, st.inline_call = st.inline_call, None
        if ic is not None and st.inline_timer is not None:
            st.inline_timer.cancel()
            st.inline_timer = None
        return ic

    def _run_inline(self, handler: RpcMethodHandler, st: _ServerStream,
                    ctx: ServerContext, path: str) -> None:
        """Inline dispatch with the reentrancy guard: first level runs on
        the calling (reader) thread; a nested inline completion reroutes
        to the pool (see _inline_tls)."""
        if getattr(_inline_tls, "active", False):
            try:
                self.server._pool.submit(self._run_handler, handler, st,
                                         ctx, path)
            except RuntimeError:  # pool shut down: server is stopping
                self._send_trailers(st, StatusCode.UNAVAILABLE,
                                    "server shutting down")
                self._finish_stream(st)
                self.close()
            return
        _inline_tls.active = True
        try:
            self._run_handler(handler, st, ctx, path)
        finally:
            _inline_tls.active = False

    def _inline_deadline(self, st: _ServerStream) -> None:
        if self._claim_inline(st) is not None:
            _flight.emit(_flight.DEADLINE_EXPIRED,
                         _SRV_INLINE_TAG, st.stream_id)
            self._send_trailers(st, StatusCode.DEADLINE_EXCEEDED,
                                "deadline exceeded awaiting request")
            self._finish_stream(st)

    def _run_handler(self, handler: RpcMethodHandler, st: _ServerStream,
                     ctx: ServerContext, path: str) -> None:
        from tpurpc.obs import watchdog as _watchdog
        from tpurpc.utils import stats as _stats

        counters = self.server.call_counters
        counters.on_start()
        ok = False
        tctx = st.trace_ctx
        if tctx is not None and st.trace_t0:
            # HEADERS arrival → handler start: the queue/handoff interval
            _tracing.record("dispatch", tctx, st.trace_t0,
                            time.monotonic_ns() - st.trace_t0, method=path)
        # tpurpc-blackbox: in-flight registration — the stall watchdog
        # sweeps these and names the blocked stage for any call past its
        # method's rolling-p99 multiple
        wd_tok = _watchdog.call_started(
            path, tctx.trace_id if tctx is not None else 0)
        t0 = time.perf_counter_ns()
        t0_mono = time.monotonic_ns()
        try:
            with _tracing.use(tctx) if tctx is not None \
                    else _tracing.NULL_CM:
                if _stats.profiling_on():  # GRPCProfiler span: handler exec
                    with _stats.profile("srv_handler"):
                        ok = self._run_handler_inner(handler, st, ctx, path)
                else:
                    ok = self._run_handler_inner(handler, st, ctx, path)
        finally:
            counters.on_finish(ok)
            _SRV_CALL_US.record((time.perf_counter_ns() - t0) // 1000)
            code = st.final_code if st.final_code is not None \
                else StatusCode.CANCELLED
            _SRV_CALLS.labels(path, int(code)).inc()
            _watchdog.call_finished(wd_tok, error=not ok)
            # tail capture: commit the provisional span tree iff this call
            # turned out pathological (slow for its method, or failed)
            _tracing.tail_decide(tctx, time.monotonic_ns() - t0_mono,
                                 error=not ok, method=path)

    def _run_handler_inner(self, handler: RpcMethodHandler, st: _ServerStream,
                           ctx: ServerContext, path: str) -> bool:
        try:
            if handler.request_streaming:
                request_in = st.request_iterator(handler.request_deserializer, ctx)
            else:
                try:
                    # Honor the declared deadline while waiting for the request
                    # body, or a silent client pins this pool worker until its
                    # connection dies.
                    item = st.next_request(timeout=ctx.deadline_remaining())
                except queue.Empty:
                    self._send_trailers(st, StatusCode.DEADLINE_EXCEEDED,
                                        "deadline exceeded awaiting request")
                    return
                if item is _ServerStream._OVERSIZED:
                    self._send_trailers(
                        st, StatusCode.RESOURCE_EXHAUSTED,
                        "received message larger than max "
                        f"({st.recv_limit} bytes)")
                    return
                if item is _ServerStream._BAD_COMPRESSION:
                    self._send_trailers(
                        st, StatusCode.INTERNAL,
                        "compressed message failed to decompress")
                    return
                if item is _ServerStream._END or not ctx.is_active():
                    if ctx.is_active():
                        self._send_trailers(
                            st, StatusCode.INVALID_ARGUMENT,
                            "client half-closed before sending a request")
                    return
                request_in = _deserialize(handler.request_deserializer, item)

            result = handler.behavior(request_in, ctx)

            if handler.response_streaming:
                for response in result:
                    if not ctx.is_active():
                        return
                    if ctx._deadline_exceeded():
                        self._send_trailers(st, StatusCode.DEADLINE_EXCEEDED,
                                            "deadline exceeded", ctx._trailing)
                        return
                    # Mirror the request's encoding, read PER SEND: for
                    # request-streaming shapes peer_compressed is only set
                    # once the lazy iterator has consumed a compressed
                    # frame — a value frozen before the generator ran
                    # would lose the mirror race.
                    self.writer.send(
                        fr.MESSAGE,
                        fr.FLAG_COMPRESSED if st.peer_compressed else 0,
                        st.stream_id,
                        handler.response_serializer(response))
                if ctx.is_active():
                    code = (ctx._code if ctx._code is not None
                            else StatusCode.OK)
                    self._send_trailers(st, code, ctx._details, ctx._trailing)
                    return code is StatusCode.OK
            elif ctx.is_active():
                # Unary response: MESSAGE + TRAILERS fused into one transport
                # write (one receiver wakeup instead of two). Serialization
                # + the gathered write are the trace timeline's "respond".
                code = ctx._code if ctx._code is not None else StatusCode.OK
                st.final_code = code
                try:
                    with (_tracing.span("respond", st.trace_ctx)
                          if st.trace_ctx is not None else _tracing.NULL_CM):
                        self.writer.send_many([
                            (fr.MESSAGE,
                             # per-send mirror read (request fully consumed
                             # by now, so peer_compressed is settled)
                             fr.FLAG_COMPRESSED if st.peer_compressed else 0,
                             st.stream_id,
                             handler.response_serializer(result)),
                            (fr.TRAILERS, fr.FLAG_END_STREAM, st.stream_id,
                             fr.trailers_payload(
                                 code, ctx._details,
                                 list(ctx._trailing)
                                 + self.server._load_md())),
                        ])
                except fr.FrameError:
                    self._send_trailers(st, StatusCode.INTERNAL,
                                        "trailing metadata too large")
                    return False
                return code is StatusCode.OK
        except AbortError as exc:
            self._send_trailers(st, exc.code, exc.details, ctx._trailing)
        except (EndpointError, OSError):
            pass  # connection already gone
        except Exception as exc:  # handler bug → UNKNOWN, like grpcio
            _log.exception("handler for %s raised", path)
            self._send_trailers(st, StatusCode.UNKNOWN,
                                f"Exception calling application: {exc}")
        finally:
            self._finish_stream(st)
        return False

    def _send_trailers(self, st: _ServerStream, code: StatusCode, details: str,
                       metadata: Metadata = ()) -> None:
        st.final_code = code
        # tpurpc-fleet: every terminal response piggybacks the (cached)
        # load report — the least_loaded policy's per-response feed
        md = list(metadata) + self.server._load_md()
        try:
            try:
                self.writer.send(fr.TRAILERS, fr.FLAG_END_STREAM, st.stream_id,
                                 fr.trailers_payload(code, details, md))
            except fr.FrameError:
                # User trailing metadata too large for one control frame: still
                # terminate the stream correctly, just without the metadata.
                self.writer.send(
                    fr.TRAILERS, fr.FLAG_END_STREAM, st.stream_id,
                    fr.trailers_payload(StatusCode.INTERNAL,
                                        "trailing metadata too large"))
        except (EndpointError, OSError):
            pass

    def _finish_stream(self, st: _ServerStream) -> None:
        with self._lock:
            self._streams.pop(st.stream_id, None)
            # admission release exactly once (the RST path and the handler
            # finally can both land here; the lock orders the take)
            gate = getattr(st, "_gate", None)
            if gate is not None:
                st._gate = None
            drained = self.draining and not self._streams and self.alive
        if gate is not None:
            gate.release()
        if drained and getattr(self, "_linger_timer", None) is None:
            # last in-flight stream after GOAWAY: close after the linger
            # (racing HEADERS still get a clean RST meanwhile)
            self._linger_then_shutdown()

    def _shutdown(self) -> None:
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            streams = list(self._streams.values())
            self._streams.clear()
        for attr in ("_age_timer", "_ka_handle", "_linger_timer"):
            h = getattr(self, attr, None)
            if h is not None:
                h.cancel()  # wheel handles; ticks also re-check alive
        if self.rdv is not None:
            # peer gone mid-rendezvous: claimed landing regions release
            self.rdv.close()
        if self.ctrl is not None:
            # descriptor rings die with the connection (a straggler's late
            # slot store lands in the orphaned mapping — dead memory)
            self.ctrl.close()
        for st in streams:
            gate = getattr(st, "_gate", None)
            if gate is not None:
                st._gate = None
                gate.release()  # connection died with the stream admitted
            st.cancel()
        try:
            self.endpoint.close()
        except Exception:
            pass
        self.server._forget(self)

    def close(self) -> None:
        try:
            self.endpoint.close()  # unblocks the reader thread
        except Exception:
            pass


class Server:
    """Thread-pooled RPC server over any Endpoint source."""

    def __init__(self, max_workers: int = 32, interceptors: Sequence = (),
                 max_receive_message_length: Optional[int] = None,
                 native_dataplane: Optional[bool] = None,
                 admission: "Optional[AdmissionGate]" = None):
        #: tpurpc extension: None = auto (adopt ring connections onto the
        #: native shared-poller loop when eligible — the small-RPC latency
        #: plane); False = always the Python plane (fully instrumented —
        #: the copy ledger counts its passes; note it is ~40% slower on
        #: multi-MiB streams since round 5 fixed the native plane's
        #: notify-token-stealing bug — 1.20 vs 0.86 GB/s same-weather,
        #: bench.py sink A/B). True behaves like auto (the eligibility
        #: gates still apply; they are correctness gates).
        self._native_dataplane_opt = native_dataplane
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="tpurpc-handler")
        self.interceptors = list(interceptors)
        #: per-message receive bound (None = config default; -1 = unlimited)
        self.max_receive_message_length = get_config().resolve_recv_limit(
            max_receive_message_length)
        from tpurpc.rpc import channelz as _channelz

        self.call_counters = _channelz.CallCounters()
        _channelz.register_server(self)
        self._methods: Dict[str, RpcMethodHandler] = {}
        self._generic_handlers: List = []  # grpcio GenericRpcHandler objects
        self._listeners: List[EndpointListener] = []
        self.bound_ports: List[int] = []
        self._connections: List[_ServerConnection] = []
        self._lock = threading.Lock()
        self._started = False
        self._stopping = False  # set under _lock before conns are torn down
        self._serving = threading.Event()
        self._stopped = threading.Event()
        # tpurpc-fleet (ISSUE 6): overload admission gate (explicit wins;
        # TPURPC_ADMISSION_MAX_INFLIGHT configures one from the env),
        # graceful-drain state, and the per-response load-report cache
        self.admission = (admission if admission is not None
                          else AdmissionGate.from_env())
        self._draining = False
        self._health_servicer = None  # set by HealthServicer.add_to_server
        import os as _os

        self._load_reports = _os.environ.get(
            "TPURPC_LOAD_REPORTS", "1").lower() not in ("0", "off", "false")
        self._load_extra: Optional[Callable[[], int]] = None
        self._load_cache: Tuple[float, Optional[list]] = (0.0, None)
        self._drain_hooks: List[Callable[[], None]] = []

    # -- registration --------------------------------------------------------

    def add_method(self, path: str, handler: RpcMethodHandler) -> None:
        self._methods[path] = handler

    def add_generic_handlers(self, handlers: Dict[str, RpcMethodHandler]) -> None:
        self._methods.update(handlers)

    # -- grpcio-generated-code compatibility ---------------------------------
    #
    # Modules generated by grpc_tools.protoc register services via
    # add_generic_rpc_handlers((generic_handler,)) and (grpcio>=1.60)
    # add_registered_method_handlers(service, {name: grpc.RpcMethodHandler}).
    # Accepting both — with grpcio's handler OBJECTS duck-adapted to ours —
    # makes `add_FooServicer_to_server(servicer, tpurpc_server)` run
    # unchanged: the mechanical-port claim for the server side.

    @staticmethod
    def _adapt_foreign_handler(h) -> Optional[RpcMethodHandler]:
        """grpc.RpcMethodHandler (any object with the grpcio attribute set)
        → our handler; None if it isn't one."""
        if isinstance(h, RpcMethodHandler):
            return h
        try:
            kind = (("stream" if h.request_streaming else "unary") + "_"
                    + ("stream" if h.response_streaming else "unary"))
            behavior = getattr(h, kind)
        except AttributeError:
            return None
        if behavior is None:
            return None
        return RpcMethodHandler(kind, behavior,
                                h.request_deserializer or _identity,
                                h.response_serializer or _identity)

    def add_generic_rpc_handlers(self, generic_handlers) -> None:
        """grpcio-shaped: a sequence of GenericRpcHandler objects whose
        ``.service(handler_call_details)`` resolves methods at call time."""
        self._generic_handlers.extend(generic_handlers)

    def add_registered_method_handlers(self, service: str,
                                       method_handlers) -> None:
        """grpcio-shaped (>=1.60): eager per-method registration."""
        for name, h in dict(method_handlers).items():
            adapted = self._adapt_foreign_handler(h)
            if adapted is not None:
                self._methods[f"/{service}/{name}"] = adapted

    def add_service(self, service: str,
                    method_handlers: Dict[str, RpcMethodHandler]) -> None:
        self.add_generic_handlers(
            method_handlers_generic_handler(service, method_handlers))

    def _lookup_intercepted(self, path: str,
                            metadata) -> Optional[RpcMethodHandler]:
        """Handler lookup through the server interceptor chain."""
        handler = self._lookup(path, metadata)
        if not self.interceptors:
            return handler
        from tpurpc.rpc.interceptors import apply_server_interceptors

        return apply_server_interceptors(handler, path, metadata,
                                         self.interceptors)

    def _lookup(self, path: str, metadata=()) -> Optional[RpcMethodHandler]:
        handler = self._methods.get(path)
        if handler is not None:
            return handler
        # grpcio-generic fallback: resolve through registered
        # GenericRpcHandler objects (duck-typed .service(details)), or plain
        # {path: handler} mappings (what tpurpc's own
        # method_handlers_generic_handler returns — pre-1.60-style generated
        # code passes those straight to add_generic_rpc_handlers).
        for gh in self._generic_handlers:
            getter = getattr(gh, "get", None)
            cacheable = getter is not None
            if cacheable:  # Mapping-shaped: metadata-independent by shape
                found = getter(path)
            else:
                try:
                    found = gh.service(_HandlerCallDetails(path, metadata))
                except Exception:
                    # a routing bug must not masquerade as UNIMPLEMENTED
                    _log.exception(
                        "generic handler %r raised resolving %s", gh, path)
                    continue
            if found is not None:
                adapted = self._adapt_foreign_handler(found)
                if adapted is not None and cacheable:
                    # hot-path cache; .service() results are NOT cached —
                    # a generic handler may route on metadata per call
                    self._methods[path] = adapted
                return adapted
        return None

    # -- ports / lifecycle ---------------------------------------------------

    def add_insecure_port(self, address: str, *,
                          reuseport: bool = False) -> int:
        """Bind now, return the real port (grpcio semantics: the port for
        ":0" must be known before start so clients can be pointed at it).

        ``reuseport=True`` is the tpurpc-manycore listener-sharding mode:
        shard workers bind the SAME port with ``SO_REUSEPORT`` and the
        kernel spreads accepts across them (see
        :class:`tpurpc.rpc.shard.ShardedServer`)."""
        host, _, port = address.rpartition(":")
        bound = self._open_port(host or "0.0.0.0", int(port),
                                reuseport=reuseport)
        self.bound_ports.append(bound)
        return bound

    def add_secure_port(self, address: str, server_credentials) -> int:
        """TLS port (grpcio-shaped): every connection handshakes before the
        protocol sniff, so native-framing, ring-bootstrap, and h2 traffic all
        ride the encrypted stream. Pass the result of
        :func:`tpurpc.rpc.credentials.ssl_server_credentials`."""
        host, _, port = address.rpartition(":")
        bound = self._open_port(host or "0.0.0.0", int(port),
                                ssl_context=server_credentials._context)
        self.bound_ports.append(bound)
        return bound

    def _open_port(self, host: str, port: int, ssl_context=None,
                   reuseport: bool = False) -> int:
        listener = EndpointListener(
            host, port, self.serve_endpoint, ready=self._serving,
            ssl_context=ssl_context,
            raw_hook=None if ssl_context is not None
            else self._try_native_adopt,
            reuseport=reuseport,
            admission=self._accept_pushback)
        self._listeners.append(listener)
        return listener.port

    def _accept_pushback(self) -> "Optional[int]":
        """Accept-path face of the admission gate (ISSUE 16): the
        listener sheds stormed connections before handshake work when the
        RPC plane is saturated."""
        gate = self.admission
        if gate is None:
            return None
        return gate.connection_pushback_ms()

    def adopt_socket(self, sock) -> None:
        """tpurpc-manycore handoff entry: serve a connection that was
        ACCEPTED ELSEWHERE (the shard supervisor's accept loop, delivered
        over SCM_RIGHTS) exactly as this server's own listener would —
        native-plane adoption probe first, then the platform endpoint
        factory, then the protocol sniff. Runs off the caller's thread: a
        ring bootstrap blocks, and the worker's control loop must not stall
        behind one silent client."""

        def _adopt():
            try:
                if self._try_native_adopt(sock):
                    return  # native data plane owns the socket now
            except Exception as exc:
                trace_server.log("handoff native probe failed (%s)", exc)
            try:
                peer = sock.getpeername()
                host = peer[0] if isinstance(peer, tuple) else str(peer)
                from tpurpc.core.endpoint import create_endpoint

                ep = create_endpoint(sock, is_server=True,
                                     pool_key=f"peer:{host}")
            except Exception as exc:
                trace_server.log("handoff bootstrap failed: %s", exc)
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self.serve_endpoint(ep)

        threading.Thread(target=_adopt, daemon=True,
                         name="tpurpc-handoff").start()

    def start(self) -> "Server":
        if self._started:
            return self
        # Native data plane (rpc/native_server.py): eligible servers hand
        # accepted ring connections to libtpurpc's shared-poller loop with
        # Python handlers trampolined back — the grpcio architecture
        # (language surface over the C core). Built at start() so every
        # registered method exists; listeners only accept after _serving.
        self._native_dp = None
        try:
            from tpurpc.rpc.native_server import (NativeDataplane,
                                                  adoption_eligible)

            if adoption_eligible(self):
                self._native_dp = NativeDataplane(self)
        except Exception as exc:  # lib unbuildable etc.: Python plane
            trace_server.log("native dataplane unavailable: %s", exc)
        self._started = True
        # tpurpc-lens (ISSUE 8): continuous stage profiling starts with the
        # server (idempotent; no-op under TPURPC_LENS=0)
        try:
            _obs_profiler.ensure_started()
        except Exception:
            pass
        # tpurpc-argus (ISSUE 14): the ring tsdb samples this process's
        # registry from the moment it serves (idempotent; TPURPC_TSDB=0
        # off), any declared SLO objectives start evaluating, and
        # TPURPC_BUNDLE_DIR arms automatic evidence capture
        try:
            from tpurpc.obs import bundle as _obs_bundle
            from tpurpc.obs import slo as _obs_slo
            from tpurpc.obs import tsdb as _obs_tsdb

            _obs_tsdb.ensure_started()
            _obs_slo.ensure_started()
            _obs_bundle.maybe_enable_from_env()
        except Exception:
            pass
        self._serving.set()  # listeners begin accepting (bound since add_port)
        return self

    def _try_native_adopt(self, sock) -> bool:
        """Raw-socket listener hook: peek the protocol magic and hand RING
        connections (TRB1 bootstrap) to the native data plane. Peeking
        (MSG_PEEK) consumes nothing, so a False return leaves the socket
        exactly as accepted for the Python path."""
        import socket as _socket

        dp = getattr(self, "_native_dp", None)
        if dp is None:
            return False
        deadline = time.monotonic() + 30
        first = b""
        try:
            sock.settimeout(2)
            while len(first) < 4 and time.monotonic() < deadline:
                try:
                    first = sock.recv(4, _socket.MSG_PEEK)
                except (TimeoutError, _socket.timeout):
                    continue
                if not first:
                    return False  # peer closed before the preface
                if len(first) < 4:
                    time.sleep(0.002)
        except OSError:
            return False
        finally:
            # EVERY False return hands the socket to the Python plane, which
            # expects it exactly as accepted (blocking); a leaked 2s timeout
            # would surface as spurious socket.timeout on slow valid reads.
            try:
                sock.settimeout(None)
            except OSError:
                pass  # already closed/reset: the caller's read will see it
        if first != b"TRB1":
            return False
        return dp.adopt(sock)

    def serve_endpoint(self, endpoint: Endpoint) -> None:
        """Adopt an already-connected endpoint, sniffing the protocol.

        The first 8 bytes decide: the TPURPC magic routes to the native
        framing; ``PRI * HT`` (the h2 connection preface) routes to the gRPC
        wire-compat path — one port serves stock gRPC clients and tpurpc
        clients simultaneously (the reference needs no sniff because it IS
        gRPC; we speak both).

        Runs the sniff on its own thread: callers (accept bootstrap, inproc
        tests) may invoke this before the client has written a byte.
        """
        threading.Thread(target=self._sniff_and_serve, args=(endpoint,),
                         daemon=True, name="tpurpc-sniff").start()

    def _sniff_and_serve(self, endpoint: Endpoint) -> None:
        first = bytearray(8)
        got = 0
        try:
            while got < 8:
                n = endpoint.read_into(memoryview(first)[got:], timeout=30)
                if n == 0:
                    endpoint.close()
                    return
                got += n
        except (EndpointError, TimeoutError):
            endpoint.close()
            return
        try:
            if bytes(first) == fr.MAGIC:
                conn = _ServerConnection(self, endpoint,
                                         preface_consumed=True)
            elif bytes(first) == b"PRI * HT":
                from tpurpc.wire.grpc_h2 import GrpcH2Connection

                conn = GrpcH2Connection(self, endpoint, preface_consumed=8)
            elif (bytes(first[:4]) == b"GET "
                  or bytes(first[:5]) == b"HEAD "):
                # tpurpc-scope introspection plane (ISSUE 4): the SAME
                # serving port answers plain-HTTP scrapes — /metrics
                # (Prometheus text), /traces (chrome trace JSON),
                # /channelz, /healthz. One request per connection, served
                # on this sniff thread, then closed. TPURPC_SCRAPE=0 off.
                from tpurpc.obs import scrape as _scrape

                if _scrape.scrape_enabled():
                    _scrape.handle_http(endpoint, bytes(first))
                else:
                    endpoint.close()
                return
            else:
                trace_server.log("unknown protocol preface %r; dropping",
                                 bytes(first))
                endpoint.close()
                return
        except (EndpointError, OSError) as exc:
            # The peer vanished mid-adoption (e.g. junk preface + close —
            # the h2 path writes SETTINGS during construction): contain it
            # to this connection instead of dying as an unhandled thread
            # exception.
            trace_server.log("peer gone during adoption: %s", exc)
            endpoint.close()
            return
        # Registration must be atomic against stop(): this sniff thread may
        # have been waiting on the preface for seconds, during which stop()
        # closed every *registered* connection and shut the pool. Adopting a
        # connection now would strand the client on a server that answers
        # every call "server shutting down" and never dies (the round-2
        # reconnect bug: client saw healthy trailers, so it never redialed).
        with self._lock:
            adopted = not self._stopping
            drain_new = self._draining
            if adopted:
                self._connections.append(conn)
        if not adopted:
            conn.close()
        elif drain_new:
            # tpurpc-fleet: a connection dialed INTO a draining server (a
            # stale resolver, or a subchannel racing the drain) is told
            # immediately — streams that race the GOAWAY get the refused
            # RST, which clients replay on another backend
            writer = getattr(conn, "writer", None)
            if writer is not None:
                with conn._lock:
                    conn.draining = True
                try:
                    writer.send(fr.GOAWAY, 0, 0, b"server drain")
                except (EndpointError, OSError, fr.FrameError):
                    pass
                conn._linger_then_shutdown()

    def _forget(self, conn: _ServerConnection) -> None:
        with self._lock:
            try:
                self._connections.remove(conn)
            except ValueError:
                pass

    def stop(self, grace: Optional[float] = None) -> threading.Event:
        for listener in self._listeners:
            listener.close()
        self._listeners.clear()
        with self._lock:
            self._stopping = True  # gate _sniff_and_serve adoptions first
            conns = list(self._connections)
        if grace:
            # Graceful semantics (grpcio parity): announce shutdown — every
            # frame-protocol connection gets a GOAWAY so clients stop
            # opening streams here (in-flight calls keep running through
            # the grace window). h2 connections have no GOAWAY sender yet;
            # they still get the drain wait below and close() after it.
            from tpurpc.wire import h2 as _h2

            for conn in conns:
                writer = getattr(conn, "writer", None)
                if writer is None:
                    # h2-protocol connection: speak h2's own GOAWAY
                    try:
                        conn._write(_h2.pack_goaway(0, 0, b"server shutdown"))
                    except Exception:
                        pass  # connection already dying
                    continue
                with conn._lock:
                    conn.draining = True
                try:
                    writer.send(fr.GOAWAY, 0, 0, b"server shutdown")
                except (EndpointError, OSError, fr.FrameError):
                    pass  # connection already dying
            deadline = time.monotonic() + grace
            while time.monotonic() < deadline:
                with self._lock:
                    busy = any(c._streams for c in self._connections)
                if not busy:
                    break
                time.sleep(0.01)
        for conn in conns:
            conn.close()
        dp = getattr(self, "_native_dp", None)
        if dp is not None:
            self._native_dp = None
            try:
                dp.close()  # tears down adopted connections + native pollers
            except Exception:
                pass
        self._pool.shutdown(wait=False)
        self._stopped.set()
        return self._stopped

    def wait_for_termination(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    def inflight_requests(self) -> int:
        """Number of currently open inbound streams across connections —
        requests admitted (HEADERS seen) whose response hasn't finished.
        The FanInBatcher's depth-aware flush probe (serve_jax wiring): when
        its queue holds this many, no further arrival can happen until
        responses go out, so it flushes instead of waiting out max_delay_s.
        A snapshot, not a fence — callers must tolerate staleness."""
        with self._lock:
            conns = list(self._connections)
        return sum(len(getattr(c, "_streams", ())) for c in conns)

    # -- fleet front door (tpurpc-fleet, ISSUE 6) -----------------------------

    def set_load_provider(self, fn: Optional[Callable[[], int]]) -> None:
        """Register an extra queue-depth signal for the load report —
        serve_jax wires the FanInBatcher's queue depth here, so the
        ``least_loaded`` policy sees requests parked BEHIND the transport
        (the batcher is where overload actually queues on a model server).
        tpurpc-keystone wires ``DecodeScheduler.load_depth`` (waiting AND
        swapped) — queue depth alone made a server holding preempted work
        look idle."""
        self._load_extra = fn

    def add_drain_hook(self, fn: Callable[[], None]) -> None:
        """Register a callback the FIRST :meth:`drain` runs after the
        GOAWAY round, before waiting out in-flight streams — the seam
        stateful serving uses to MIGRATE live sequences to a peer instead
        of merely finishing them (tpurpc-keystone: the zero-failed-RPC
        drain contract extended to generation state). Hooks run on the
        draining thread; exceptions are swallowed (a failed hook degrades
        to a plain drain, never a stuck one)."""
        self._drain_hooks.append(fn)

    def _load_md(self) -> list:
        """The ORCA-style piggyback: ``[(LOAD_KEY, "i,q,p99ms")]`` appended
        to every terminal response's trailing metadata, or ``[]`` when
        disabled (``TPURPC_LOAD_REPORTS=0``).

        Cached ~20 ms so the per-response cost is one monotonic read plus a
        list concat — load is a trend, not a fence, and the client-side
        EWMA smooths staleness anyway. Inflight comes from the admission
        gate's own counter when one is installed (no lock sweep), else from
        :meth:`inflight_requests`."""
        if not self._load_reports:
            return []
        now = time.monotonic()
        stamp, cached = self._load_cache
        if cached is not None and now - stamp < 0.02:
            return cached
        gate = self.admission
        inflight = (gate.inflight() if gate is not None
                    else self.inflight_requests())
        qdepth = 0
        extra = self._load_extra
        if extra is not None:
            try:
                qdepth = int(extra())
            except Exception:
                qdepth = 0
        p99_ms = 0.0
        try:
            from tpurpc.obs import watchdog as _watchdog

            p99 = _watchdog.get().rolling_p99_ns()
            if p99:
                p99_ms = p99 / 1e6
        except Exception:
            pass
        md = [(LOAD_KEY, f"{inflight},{qdepth},{p99_ms:.1f}")]
        self._load_cache = (now, md)
        return md

    @property
    def draining(self) -> bool:
        """True between :meth:`drain` and :meth:`stop` — /healthz reports
        ``draining`` and the health service answers NOT_SERVING. A stopped
        server is not draining (it is gone): /healthz on a process whose
        old server object lingers must not keep reporting the drain."""
        return self._draining and not self._stopped.is_set()

    def drain(self, linger: float = 5.0) -> bool:
        """Server-wide graceful drain: announce, bleed, never fail a call.

        Generalizes the per-connection ``max_connection_age`` path to the
        whole server: (1) the attached health servicer (if any) flips every
        service to NOT_SERVING so LBs stop routing here; (2) every live
        connection gets a GOAWAY — clients stop opening streams on it and
        dial elsewhere; streams that race the GOAWAY are refused with
        FLAG_REFUSED, which clients replay on another subchannel
        (zero failed RPCs); (3) in-flight streams run to completion under
        the ``linger`` budget. Connections opened DURING the drain are
        GOAWAY'd at adoption, so a stale resolver can't keep feeding this
        backend.

        The server object stays alive (listeners answer /healthz scrapes
        and health RPCs — orchestrators need the probe plane up while
        connections bleed); call :meth:`stop` once traffic has moved.
        Returns True iff every in-flight stream finished within the budget.
        Idempotent: a second call just re-waits the remaining streams."""
        with self._lock:
            first = not self._draining
            self._draining = True
            conns = list(self._connections)
        n_conns = len(conns)
        if first:
            _flight.emit(_flight.DRAIN_BEGIN, _SRV_DRAIN_TAG, n_conns)
            hs = self._health_servicer
            if hs is not None:
                from tpurpc.rpc.health import ServingStatus

                hs.set_all(ServingStatus.NOT_SERVING)
            from tpurpc.wire import h2 as _h2

            for conn in conns:
                writer = getattr(conn, "writer", None)
                if writer is None:
                    # h2-protocol connection: speak h2's own GOAWAY
                    try:
                        conn._write(_h2.pack_goaway(0, 0, b"server drain"))
                    except Exception:
                        pass  # connection already dying
                    continue
                with conn._lock:
                    if not conn.alive or conn.draining:
                        continue
                    conn.draining = True
                    empty = not conn._streams
                try:
                    writer.send(fr.GOAWAY, 0, 0, b"server drain")
                except (EndpointError, OSError, fr.FrameError):
                    continue  # connection already dying
                if empty:
                    # no in-flight streams: close after the refused-HEADERS
                    # linger (the max_age path's exact contract)
                    conn._linger_then_shutdown()
            # stateful-serving seam: migrate live sequences BEFORE the
            # in-flight wait, so streams end with re-attach records (and
            # stop counting against the linger) instead of running out
            # their full generations here
            for hook in list(self._drain_hooks):
                try:
                    hook()
                except Exception:
                    pass  # a failed hook degrades to a plain drain
        deadline = time.monotonic() + max(0.0, linger)
        while True:
            with self._lock:
                # health probes (Check + held-open Watch streams) are
                # admitted during drain and must not count against it
                remaining = sum(
                    1
                    for c in self._connections
                    for st in list(getattr(c, "_streams", {}).values())
                    if not getattr(st, "is_probe", False))
            if remaining == 0 or time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        if first:
            _flight.emit(_flight.DRAIN_END, _SRV_DRAIN_TAG, remaining)
        return remaining == 0


def server(thread_pool=None, handlers=None, interceptors=None, options=None,
           maximum_concurrent_rpcs=None, compression=None, *,
           max_workers: int = 32) -> Server:
    """grpcio-shaped constructor — accepts the stock call
    ``grpc.server(ThreadPoolExecutor(max_workers=N), options=[...])``
    verbatim: a passed executor contributes its worker count (the Server
    keeps its own pool), handlers/interceptors register directly, the
    recognized channel-arg options map onto Server parameters, and the
    remaining stock kwargs are accepted-and-advisory
    (maximum_concurrent_rpcs — concurrency is bounded by the worker pool
    and per-stream credits instead; compression is negotiated per wire).
    A bare int first argument keeps the historical server(N) meaning."""
    if isinstance(thread_pool, int):  # legacy positional max_workers
        max_workers = thread_pool
    elif thread_pool is not None:
        workers = getattr(thread_pool, "_max_workers", None)
        if workers:
            max_workers = workers
    max_recv = None
    if options:
        max_recv = dict(options).get("grpc.max_receive_message_length")
    srv = Server(max_workers=max_workers, interceptors=interceptors or (),
                 max_receive_message_length=max_recv)
    if handlers:
        srv.add_generic_rpc_handlers(handlers)
    return srv


def inproc_channel(srv: Server):
    """In-process channel↔server wiring over a passthru endpoint pair — the
    reference's inproc transport (``src/core/ext/transport/inproc/``) as a seam."""
    from tpurpc.rpc.channel import Channel

    def factory():
        a, b = passthru_endpoint_pair()
        srv.serve_endpoint(b)
        return a

    return Channel(endpoint_factory=factory)
