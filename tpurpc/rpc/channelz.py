"""channelz-lite: live introspection of servers and channels.

The reference inherits gRPC's channelz service (``src/cpp/server/channelz/``,
SURVEY.md §5 tracing row). This is the same capability without the protobuf
service wrapper: a process-wide registry + JSON-able stat dicts, exposed both
programmatically and as a registrable tensor/bytes RPC method so remote
inspection works over tpurpc itself.
"""

from __future__ import annotations

import json
import time
import weakref
from typing import Dict

from tpurpc.analysis.locks import make_lock

_lock = make_lock("channelz._lock")
_servers: "weakref.WeakSet" = weakref.WeakSet()
_channels: "weakref.WeakSet" = weakref.WeakSet()


class CallCounters:
    """started/succeeded/failed + last-activity timestamps (channelz core).

    Lock-guarded: one instance is shared by every thread of a channel or
    server, and ``+=`` is a read-modify-write the GIL can split."""

    __slots__ = ("started", "succeeded", "failed", "last_call_started",
                 "_mu")

    #: lock map, checked by `python -m tpurpc.analysis` (lint rule `lock`)
    _GUARDED_BY = {"started": "_mu", "succeeded": "_mu", "failed": "_mu",
                   "last_call_started": "_mu"}

    def __init__(self):
        self.started = 0
        self.succeeded = 0
        self.failed = 0
        self.last_call_started = 0.0
        self._mu = make_lock("CallCounters._mu")

    def on_start(self) -> None:
        with self._mu:
            self.started += 1
            # channelz REPORTS this as an absolute wall timestamp
            self.last_call_started = time.time()  # tpr: allow(wallclock)

    def on_finish(self, ok: bool) -> None:
        with self._mu:
            if ok:
                self.succeeded += 1
            else:
                self.failed += 1

    def as_dict(self) -> Dict:
        # snapshot under the same lock as the writers: a reader between the
        # started += 1 and the timestamp store would report a call count
        # with the previous call's timestamp (unlocked-snapshot window)
        with self._mu:
            return {"calls_started": self.started,
                    "calls_succeeded": self.succeeded,
                    "calls_failed": self.failed,
                    "last_call_started": self.last_call_started}


_next_id = 0


def _assign_id(obj) -> None:
    global _next_id
    _next_id += 1
    obj._channelz_id = _next_id


def register_server(srv) -> None:
    with _lock:
        _assign_id(srv)
        _servers.add(srv)


def register_channel(ch) -> None:
    with _lock:
        _assign_id(ch)
        _channels.add(ch)


def live_servers():
    """(id, server) pairs, id-ordered (channelz v1 pagination contract)."""
    with _lock:
        return sorted(((s._channelz_id, s) for s in _servers))


def live_channels():
    with _lock:
        return sorted(((c._channelz_id, c) for c in _channels))


def socket_id_for(obj, port: int) -> int:
    """Stable channelz id for a socket-like entity (a server's listen port
    or a live connection), drawn from the same entity-id space as
    servers/channels (global uniqueness contract). The id is stored ON the
    object — it dies with it (a registry keyed by ``id(obj)`` would grow
    forever and alias recycled ids)."""
    global _next_id
    attr = f"_channelz_sock_{port}"
    sid = getattr(obj, attr, None)
    if sid is None:
        with _lock:
            sid = getattr(obj, attr, None)  # double-check under the lock
            if sid is None:
                _next_id += 1
                sid = _next_id
                try:
                    setattr(obj, attr, sid)
                except AttributeError:
                    pass  # __slots__ object: fall back to a fresh id per call
    return sid


def server_info(srv) -> Dict:
    conns = list(getattr(srv, "_connections", []))
    info = {
        "ports": list(getattr(srv, "bound_ports", [])),
        "methods": sorted(srv._methods.keys()),
        "connections": len(conns),
        # connection-management state (keepalive/max_age drain visibility)
        "draining_connections": sum(
            1 for c in conns if getattr(c, "draining", False)),
        "active_streams": sum(len(getattr(c, "_streams", ())) for c in conns),
        "interceptors": len(getattr(srv, "interceptors", [])),
    }
    counters = getattr(srv, "call_counters", None)
    if counters is not None:
        info.update(counters.as_dict())
    return info


def channel_info(ch) -> Dict:
    subs = getattr(ch, "_subchannels", [])
    live = [s._conn for s in subs if s._conn is not None and s._conn.alive]
    return {
        "subchannels": len(subs),
        "connected": len(live),
        "draining": sum(1 for c in live if getattr(c, "draining", False)),
        "active_streams": sum(len(getattr(c, "_streams", ())) for c in live),
        "lb_policy": getattr(getattr(ch, "_policy", None), "name", "?"),
        "closed": ch._is_closed(),
    }


def snapshot() -> Dict:
    with _lock:
        servers = list(_servers)
        channels = list(_channels)
    return {
        "servers": [server_info(s) for s in servers],
        "channels": [channel_info(c) for c in channels],
    }


def add_channelz_service(srv) -> None:
    """Expose the snapshot as ``/tpurpc.Channelz/Get`` (bytes → JSON bytes)."""
    from tpurpc.rpc.server import unary_unary_rpc_method_handler

    srv.add_method(
        "/tpurpc.Channelz/Get",
        unary_unary_rpc_method_handler(
            lambda _req, _ctx: json.dumps(snapshot()).encode()))
