"""Envoy xDS v3 ADS wire codec — the REAL protocol for EDS.

Round-4's xDS-lite spoke a custom JSON control-plane protocol; a stock
control plane (go-control-plane, Istio) could not serve it. This module
adds the actual v3 surface for the one resource type tpurpc consumes —
cluster load assignments (EDS) — in the same hand-rolled-codec style the
repo already proved against real protobuf for grpc.lb.v1
(:mod:`tpurpc.rpc.lb_v1`, validated in ``tests/test_lookaside.py``).

Wire shape (``/root/reference/src/core/ext/filters/client_channel/
resolver/xds/`` consumes the same stream through its XdsClient):

    /envoy.service.discovery.v3.AggregatedDiscoveryService/
        StreamAggregatedResources            (bidi)

    DiscoveryRequest  { string version_info = 1; Node node = 2;
                        repeated string resource_names = 3;
                        string type_url = 4; string response_nonce = 5; }
    Node              { string id = 1; string cluster = 2;
                        string user_agent_name = 6; }
    DiscoveryResponse { string version_info = 1;
                        repeated google.protobuf.Any resources = 2;
                        string type_url = 4; string nonce = 5; }
    Any               { string type_url = 1; bytes value = 2; }

    ClusterLoadAssignment (envoy.config.endpoint.v3) {
        string cluster_name = 1;
        repeated LocalityLbEndpoints endpoints = 2; }
    LocalityLbEndpoints { repeated LbEndpoint lb_endpoints = 2;
                          uint32 priority = 5; }
    LbEndpoint  { Endpoint endpoint = 1; HealthStatus health_status = 2; }
    Endpoint    { Address address = 1; }
    Address     { SocketAddress socket_address = 1; }
    SocketAddress { string address = 2; uint32 port_value = 3; }

Unknown fields are skipped everywhere (proto3 semantics), so responses
from real control planes — which populate far more of these messages —
decode fine. LDS/RDS/CDS and the c2p resolver stay scoped out (VERDICT
r4 next #7): this is the EDS endpoint-feed, the piece tpurpc's channel
actually consumes via ``update_addresses``.

The ACK protocol (XdsWatcher._run_v3): every DECODABLE DiscoveryResponse
is answered with a DiscoveryRequest echoing ``version_info`` +
``response_nonce`` — even when its assignment is unusable, so an
ACK-gated control plane never stalls. A response that does not decode at
all is skipped without ACK (its nonce is unreadable, so a NACK is not
possible either); NACK-with-error_detail is not implemented.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from tpurpc.wire.protowire import fields, ld, vf

SERVICE = "envoy.service.discovery.v3.AggregatedDiscoveryService"
METHOD = f"/{SERVICE}/StreamAggregatedResources"
CLA_TYPE_URL = ("type.googleapis.com/"
                "envoy.config.endpoint.v3.ClusterLoadAssignment")

#: HealthStatus values that mean "dial this" (UNKNOWN=0 and HEALTHY=1 —
#: envoy treats UNKNOWN as healthy; everything else is excluded)
_DIALABLE_HEALTH = (0, 1)


def _s(field_no: int, text: str) -> bytes:
    return ld(field_no, text.encode()) if text else b""


# -- DiscoveryRequest ---------------------------------------------------------

def encode_discovery_request(resource_names: Sequence[str],
                             type_url: str = CLA_TYPE_URL,
                             version_info: str = "",
                             response_nonce: str = "",
                             node_id: str = "",
                             node_cluster: str = "") -> bytes:
    node = _s(1, node_id) + _s(2, node_cluster) + _s(6, "tpurpc")
    out = _s(1, version_info)
    if node:
        out += ld(2, node)
    for name in resource_names:
        out += ld(3, name.encode())
    out += _s(4, type_url) + _s(5, response_nonce)
    return out


def decode_discovery_request(buf) -> dict:
    """{"version_info", "resource_names", "type_url", "response_nonce",
    "node_id"} — the control-plane side's view of a subscribe/ACK."""
    out = {"version_info": "", "resource_names": [], "type_url": "",
           "response_nonce": "", "node_id": ""}
    for fno, wt, val in fields(bytes(buf)):
        if wt != 2:
            continue
        if fno == 1:
            out["version_info"] = val.decode("utf-8", "replace")
        elif fno == 2:
            for nfno, nwt, nval in fields(val):
                if nfno == 1 and nwt == 2:
                    out["node_id"] = nval.decode("utf-8", "replace")
        elif fno == 3:
            out["resource_names"].append(val.decode("utf-8", "replace"))
        elif fno == 4:
            out["type_url"] = val.decode("utf-8", "replace")
        elif fno == 5:
            out["response_nonce"] = val.decode("utf-8", "replace")
    return out


# -- ClusterLoadAssignment ----------------------------------------------------

def encode_cluster_load_assignment(cluster_name: str,
                                   endpoints: Sequence[str],
                                   priority: int = 0) -> bytes:
    """One locality holding every endpoint (the common flat case a test
    control plane emits; real planes shard by locality and the decoder
    flattens them back). Unparsable "host:port" strings are SKIPPED (the
    lb_v1 encoder's rule): a control plane crashing its own push stream
    on one malformed assignment entry would wedge every subscriber."""
    lb_eps = b""
    for addr in endpoints:
        host, _, port_s = addr.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            continue  # no/garbage port: SocketAddress cannot carry it
        if not host:
            continue
        sock = _s(2, host.strip("[]")) + vf(3, port)
        lb_eps += ld(2, ld(1, ld(1, ld(1, sock))))
    locality = lb_eps + vf(5, priority)
    return _s(1, cluster_name) + ld(2, locality)


def decode_cluster_load_assignment(buf) -> Tuple[str, List[str]]:
    """→ (cluster_name, ["host:port", ...]) across ALL localities, ordered
    by priority (stable within a locality), unhealthy endpoints excluded."""
    cluster = ""
    localities: List[Tuple[int, List[str]]] = []
    for fno, wt, val in fields(bytes(buf)):
        if fno == 1 and wt == 2:
            cluster = val.decode("utf-8", "replace")
        elif fno == 2 and wt == 2:
            prio = 0
            addrs: List[str] = []
            for lfno, lwt, lval in fields(val):
                if lfno == 5 and lwt == 0:
                    prio = lval
                elif lfno == 2 and lwt == 2:  # LbEndpoint
                    health = 0
                    hostport = None
                    for efno, ewt, eval_ in fields(lval):
                        if efno == 2 and ewt == 0:
                            health = eval_
                        elif efno == 1 and ewt == 2:  # Endpoint
                            for afno, awt, aval in fields(eval_):
                                if afno == 1 and awt == 2:  # Address
                                    hostport = _decode_address(aval)
                    if hostport and health in _DIALABLE_HEALTH:
                        addrs.append(hostport)
            localities.append((prio, addrs))
    localities.sort(key=lambda t: t[0])
    flat: List[str] = []
    for _, addrs in localities:
        flat.extend(addrs)
    return cluster, flat


def _decode_address(buf) -> Optional[str]:
    for fno, wt, val in fields(buf):
        if fno == 1 and wt == 2:  # SocketAddress
            host = ""
            port = 0
            for sfno, swt, sval in fields(val):
                if sfno == 2 and swt == 2:
                    host = sval.decode("utf-8", "replace")
                elif sfno == 3 and swt == 0:
                    port = sval
            if host:
                return f"[{host}]:{port}" if ":" in host else f"{host}:{port}"
    return None


# -- DiscoveryResponse --------------------------------------------------------

def encode_discovery_response(assignments: Sequence[Tuple[str,
                                                          Sequence[str]]],
                              version_info: str, nonce: str) -> bytes:
    out = _s(1, version_info)
    for cluster, endpoints in assignments:
        cla = encode_cluster_load_assignment(cluster, endpoints)
        out += ld(2, _s(1, CLA_TYPE_URL) + ld(2, cla))
    out += _s(4, CLA_TYPE_URL) + _s(5, nonce)
    return out


def decode_discovery_response(buf) -> dict:
    """{"version_info", "nonce", "type_url",
    "assignments": {cluster: [addr, ...]}} — non-CLA resources skipped."""
    out = {"version_info": "", "nonce": "", "type_url": "",
           "assignments": {}}
    for fno, wt, val in fields(bytes(buf)):
        if wt != 2:
            continue
        if fno == 1:
            out["version_info"] = val.decode("utf-8", "replace")
        elif fno == 4:
            out["type_url"] = val.decode("utf-8", "replace")
        elif fno == 5:
            out["nonce"] = val.decode("utf-8", "replace")
        elif fno == 2:  # Any
            a_type = ""
            a_val = b""
            for afno, awt, aval in fields(val):
                if afno == 1 and awt == 2:
                    a_type = aval.decode("utf-8", "replace")
                elif afno == 2 and awt == 2:
                    a_val = aval
            if a_type == CLA_TYPE_URL:
                cluster, addrs = decode_cluster_load_assignment(a_val)
                if cluster:
                    out["assignments"][cluster] = addrs
    return out


__all__ = ["SERVICE", "METHOD", "CLA_TYPE_URL",
           "encode_discovery_request", "decode_discovery_request",
           "encode_cluster_load_assignment",
           "decode_cluster_load_assignment",
           "encode_discovery_response", "decode_discovery_response"]
