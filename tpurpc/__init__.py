"""tpurpc — a TPU-native RPC framework with the capability set of pwrliang/grpc-rdma.

The reference ("RR-Compound", /root/reference) is a gRPC v1.38 fork that swaps the byte
transport under gRPC's endpoint abstraction from TCP to one-sided-write RDMA ring buffers,
selected at runtime by the ``GRPC_PLATFORM_TYPE`` env var (reference:
``src/core/lib/iomgr/iomgr_internal.cc:36-61``).  tpurpc rebuilds that capability seam
TPU-first:

* the swappable byte-pipe lives behind one :class:`tpurpc.core.endpoint.Endpoint`
  interface (reference: ``src/core/lib/iomgr/endpoint.h``),
* the high-performance paths are credit-managed header/footer-framed ring buffers
  (reference: ``src/core/lib/ibverbs/ring_buffer.{h,cc}``) written by one-sided ops,
  with three wakeup disciplines — busy-poll, event-driven, hybrid (reference engines
  ``ev_epollex_rdma_{bp,event,bpev}_linux.cc``),
* receive rings can live in TPU HBM and surface payloads as zero-copy ``jax.Array``s
  (this repo's north star; the reference always copies ring→slice,
  ``ring_buffer.cc:122-191``),
* the wire format is gRPC-compatible (HTTP/2 + length-prefixed messages) so stock
  grpcio clients interoperate.

Package map (SURVEY.md §7):

=================  ===========================================================
``tpurpc.utils``   config / trace / logging / sync plumbing (ref: gpr, gprpp)
``tpurpc.core``    ring, pair, poller, endpoint, tcp, wire (ref: iomgr, ibverbs)
``tpurpc.rpc``     call/stream layer, server, client (ref: surface/, chttp2)
``tpurpc.tpu``     HBM rings, copy ledger, device serialization (north star)
``tpurpc.jaxshim`` grpcio-jax: jax.Array in/out, tensor services, pjit serving
``tpurpc.models``  flagship serving models (ResNet-50 inference server)
``tpurpc.ops``     Pallas/XLA device kernels used by the data plane
``tpurpc.parallel`` mesh/sharding helpers for multi-chip serving
=================  ===========================================================
"""

from tpurpc.version import __version__
from tpurpc.utils.config import Config, Platform

__all__ = ["__version__", "Config", "Platform"]
