"""Bounded MPMC handoff ring: the shard → device-merger boundary.

tpurpc-manycore's rule is *no cross-shard locking on the hot path*: per-core
batcher shards must publish ready sub-batches toward the device without ever
contending on a shared mutex. This is the classic bounded MPMC queue in the
Vyukov style, specialized to tpurpc's shape — N producers (one per batcher
shard), ONE consumer (the device merger):

* each slot carries a **sequence stamp** ``_seq[i]`` (initialized to ``i``);
* a producer **claims** a ticket ``t`` with one ``next()`` on an
  ``itertools.count`` — a single GIL-atomic step, the Python analog of
  ``fetch_add`` (the claim is the whole MPMC subtlety: two producers must
  never own one slot, which is exactly the ``handoff_torn_claim`` mutant the
  model checker kills);
* the producer waits for ``_seq[slot] == t`` (the slot's previous lap has
  been consumed), stores the payload, then **commits** with
  ``_seq[slot] = t + 1`` — the commit stamp is the only publish gate, stored
  strictly after the payload (mutant ``handoff_commit_before_write``);
* the single consumer takes slots in ticket order, gated on
  ``_seq[slot] == head + 1`` (reading without the gate is mutant
  ``handoff_read_uncommitted``), and frees the slot for lap N+1 with
  ``_seq[slot] = head + capacity``.

The protocol is modeled word-for-word in
:func:`tpurpc.analysis.ringcheck.check_handoff`, which exhaustively
interleaves two producers against the merger and kills all three seeded
mutants — the same checked-invariant discipline the SPSC data ring has had
since PR 2.

Events here are WAKEUPS only (a parked peer learns the state changed), never
guards: every ordering claim rests on the stamp protocol above. A full ring
blocks the producer — that is the backpressure path, deliberately cold.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

__all__ = ["HandoffRing"]


class HandoffRing:
    """N-producer / 1-consumer bounded handoff (see module docstring).

    ``publish`` is the shard-side hot path: one atomic ticket claim, one
    list store, one stamp store, one event set. ``take``/``take_ready`` are
    consumer-only — exactly one thread (the merger) may call them.
    """

    def __init__(self, capacity: int = 64):
        if capacity < 2:
            raise ValueError("handoff ring needs capacity >= 2")
        self._cap = capacity
        self._slots: List[object] = [None] * capacity
        #: per-slot sequence stamps — THE protocol (module docstring);
        #: plain-int list stores are GIL-atomic, mirroring the model's
        #: one-word-store granularity
        self._seq: List[int] = list(range(capacity))
        self._ticket = itertools.count()  # atomic claim: one next() bytecode
        self._head = 0  # consumer-private
        self._data_evt = threading.Event()
        self._space_evt = threading.Event()
        self._closed = False

    def __len__(self) -> int:
        """Approximate occupancy — committed, unconsumed slots in ticket
        order from the consumer head (racy snapshot; load reporting only)."""
        h = self._head
        n = 0
        for off in range(self._cap):
            if self._seq[(h + off) % self._cap] == h + off + 1:
                n += 1
            else:
                break
        return n

    # -- producer side (per-shard batcher threads) ---------------------------

    def publish(self, item, timeout: Optional[float] = None) -> bool:
        """Publish one item; False if the ring closed (or ``timeout`` passed
        while full — backpressure). Safe from any number of threads."""
        t = next(self._ticket)  # atomic claim
        slot = t % self._cap
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._seq[slot] != t:
            # the slot's previous lap is not consumed yet: ring full for
            # THIS producer — park until the merger frees it (cold path)
            if self._closed:
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            self._space_evt.wait(0.01)
            self._space_evt.clear()
        if self._closed:
            return False
        self._slots[slot] = item
        self._seq[slot] = t + 1  # COMMIT: stored strictly after the payload
        self._data_evt.set()
        return True

    # -- consumer side (the device merger thread, singular) ------------------

    def take(self, timeout: Optional[float] = None):
        """Next item in ticket order; None on close-and-drained or timeout."""
        h = self._head
        slot = h % self._cap
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._seq[slot] != h + 1:  # commit gate
            if self._closed:
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            self._data_evt.wait(0.05)
            self._data_evt.clear()
        item, self._slots[slot] = self._slots[slot], None
        self._seq[slot] = h + self._cap  # free the slot for lap N+1
        self._head = h + 1
        self._space_evt.set()
        return item

    def take_ready(self):
        """Non-blocking take: the merger's gather pass (drain whatever the
        other shards already committed). None when nothing is ready."""
        h = self._head
        slot = h % self._cap
        if self._seq[slot] != h + 1:
            return None
        item, self._slots[slot] = self._slots[slot], None
        self._seq[slot] = h + self._cap
        self._head = h + 1
        self._space_evt.set()
        return item

    def close(self) -> None:
        self._closed = True
        self._data_evt.set()
        self._space_evt.set()
