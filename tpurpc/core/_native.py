"""ctypes loader for the native data-plane core (native/build/libtpurpc.so).

The reference's entire data plane is C++ (``src/core/lib/ibverbs/``); ours
keeps the state machines in Python and pushes the per-byte work — framed-ring
scan/copy/zero with proper acquire/release fences — into C++. Pure-Python
fallbacks stay in tpurpc/core/ring.py; ``TPURPC_NATIVE=0`` forces them (both
paths are covered by the same test suite).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: "Optional[ctypes.CDLL]" = None
_SPIN: "Optional[ctypes.CDLL]" = None
_TRIED = False

ABI_VERSION = 7


def _lib_path() -> str:
    # TPURPC_NATIVE_LIB points the loader at an alternate artifact — e.g. a
    # TPURPC_SANITIZE=thread build (tools/check.sh) — without clobbering the
    # release .so. A sanitized lib additionally needs the sanitizer runtime
    # preloaded into the (uninstrumented) Python process:
    #   LD_PRELOAD=libtsan.so.0 TPURPC_NATIVE_LIB=… python -m pytest …
    override = os.environ.get("TPURPC_NATIVE_LIB")
    if override:
        return override
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "native", "build", "libtpurpc.so")


def _try_build(path: str) -> None:
    """Best-effort first-use build of the native core (fresh checkouts ship
    sources only). One direct g++ invocation — no cmake dependency — guarded
    by an exclusive lockfile so concurrent processes don't race the link;
    losers wait for the winner. Failure is fine: callers fall back to the
    pure-Python data plane. ``TPURPC_NATIVE_BUILD=0`` disables."""
    import shutil
    import subprocess

    if os.environ.get("TPURPC_NATIVE_BUILD", "1") == "0":
        return
    if os.environ.get("TPURPC_NATIVE_LIB"):
        return  # an explicitly pointed-at artifact is never auto-built
    gxx = shutil.which("g++")
    if gxx is None:
        return
    import glob

    build_dir = os.path.dirname(path)
    srcs = sorted(glob.glob(
        os.path.join(os.path.dirname(build_dir), "src", "*.cc")))
    if not srcs:
        return
    os.makedirs(build_dir, exist_ok=True)
    lock_path = os.path.join(build_dir, ".build.lock")
    fail_stamp = os.path.join(build_dir, ".build.failed")
    try:
        import fcntl

        src_mtime = max(os.path.getmtime(s) for s in srcs)
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)  # winner builds, losers wait here
            if os.path.exists(fail_stamp):
                # A prior attempt failed. Honor the stamp only while the
                # sources are unchanged — newer sources (a fix, a git pull)
                # invalidate it, as does a stamp older than the sources on
                # disk. A transient failure (loaded machine) is retried by
                # touching the sources or deleting native/build.
                try:
                    with open(fail_stamp) as f:
                        stamped = float(f.readline().strip() or 0)
                except (OSError, ValueError):
                    stamped = 0.0
                if stamped >= src_mtime:
                    return
                os.unlink(fail_stamp)
            if not os.path.exists(path):
                tmp = path + ".tmp"
                try:
                    # -lrt: shm_open/shm_unlink live in librt on older glibc
                    # (< 2.34); without it the link "succeeds" but dlopen
                    # fails with an undefined-symbol error and the whole
                    # native data plane silently falls back to Python — the
                    # exact failure observed on this host. Harmless where
                    # libc already provides them.
                    subprocess.run(
                        [gxx, "-std=c++17", "-O3", "-DNDEBUG", "-shared",
                         "-fPIC", *srcs, "-o", tmp, "-lpthread", "-lrt"],
                        check=True, timeout=120, capture_output=True)
                except Exception as exc:
                    # Stamp the failure so future processes skip the broken
                    # 120s compile until the sources change.
                    with open(fail_stamp, "w") as f:
                        f.write(f"{src_mtime}\n{type(exc).__name__}: {exc}\n")
                    return
                os.replace(tmp, path)  # atomic: no partially-linked .so visible
    except Exception:
        pass


def load() -> "Optional[ctypes.CDLL]":
    """The native library, or None (absent, disabled, or ABI-mismatched)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("TPURPC_NATIVE", "1") == "0":
        return None
    path = _lib_path()
    if not os.path.exists(path):
        _try_build(path)
    if not os.path.exists(path):
        return None
    try:
        # PyDLL: calls run WITH the GIL held. The ring ops take raw pointers
        # into shm segments whose lifetime is managed by Python memoryview
        # release + munmap on other threads; holding the GIL makes each
        # [liveness-check → native call] pair atomic against teardown, the
        # exact safety the pure-Python slicing path gets implicitly.
        lib = ctypes.PyDLL(path)
    except OSError:
        # A stale or mis-linked artifact fails dlopen (observed: a build
        # without -lrt leaves shm_open undefined on older glibc). Rebuild
        # from sources once instead of silently dropping the whole native
        # data plane to Python for the life of the process.
        try:
            os.unlink(path)
        except OSError:
            return None
        _try_build(path)
        if not os.path.exists(path):
            return None
        try:
            lib = ctypes.PyDLL(path)
        except OSError:
            return None
    if lib.tpr_abi_version() != ABI_VERSION:
        # A stale artifact from an older checkout: rebuild from the sources
        # on disk instead of silently dropping the native data plane (the
        # same recovery the dlopen-failure path gets). An explicitly
        # pointed-at TPURPC_NATIVE_LIB is never deleted or rebuilt.
        if os.environ.get("TPURPC_NATIVE_LIB"):
            return None
        try:
            os.unlink(path)
        except OSError:
            return None
        _try_build(path)
        if not os.path.exists(path):
            return None
        try:
            lib = ctypes.PyDLL(path)
        except OSError:
            return None
        if lib.tpr_abi_version() != ABI_VERSION:
            return None
    u64 = ctypes.c_uint64
    pu64 = ctypes.POINTER(u64)
    pu8 = ctypes.c_void_p
    lib.tpr_ring_readable.restype = u64
    lib.tpr_ring_readable.argtypes = [pu8, u64, u64, u64, u64, u64]
    lib.tpr_ring_read_into.restype = u64
    lib.tpr_ring_read_into.argtypes = [pu8, u64, pu64, pu64, pu64, pu8, u64,
                                       pu64, pu64]
    lib.tpr_ring_writev.restype = u64
    lib.tpr_ring_writev.argtypes = [pu8, u64, pu64, u64,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    pu64, ctypes.c_uint32, pu64]
    lib.tpr_ring_has_message.restype = ctypes.c_int
    lib.tpr_ring_has_message.argtypes = [pu8, u64, u64, u64, u64]
    # waiter-advertisement words (futex-style sleep handshake; see ring.cc)
    lib.tpr_store_u64_seqcst.restype = None
    lib.tpr_store_u64_seqcst.argtypes = [pu8, u64]
    lib.tpr_load_u64_fenced.restype = u64
    lib.tpr_load_u64_fenced.argtypes = [pu8]
    # fused hot-path send: credit fold + chunked gather-encode + notify
    # decision in one GIL-held call (see ring.cc tpr_send_fast)
    lib.tpr_send_fast.restype = u64
    lib.tpr_send_fast.argtypes = [pu8, u64, pu64, pu64, pu8, pu64, pu8,
                                  ctypes.POINTER(ctypes.c_void_p), pu64,
                                  ctypes.c_uint32, u64,
                                  ctypes.POINTER(ctypes.c_int)]
    _LIB = lib

    # Second handle via CDLL: these calls RELEASE the GIL — they are the
    # bounded busy-poll windows (BP/BPEV disciplines), and a spinning waiter
    # must not starve the very threads that produce what it waits for.
    # Callers pin the watched memory (an exported buffer view) across the
    # call; Region.close retries on BufferError until waiters unpin.
    spin = ctypes.CDLL(path)
    spin.tpr_ring_wait_message.restype = ctypes.c_int
    spin.tpr_ring_wait_message.argtypes = [pu8, u64, u64, u64, u64]
    spin.tpr_spin_u64_change.restype = ctypes.c_int
    spin.tpr_spin_u64_change.argtypes = [pu8, u64, u64]
    global _SPIN
    _SPIN = spin
    return _LIB


def load_spin() -> "Optional[ctypes.CDLL]":
    """GIL-releasing spin-wait entry points (None when native is unavailable)."""
    load()
    return _SPIN


def addr_of(buf, writable: bool) -> int:
    """Raw address of a buffer-protocol object without copying.

    numpy handles both read-only and writable exporters; the array is a view,
    so the caller must keep ``buf`` alive for the duration of the native call.
    """
    return pin(buf, writable)[1]


def pin(buf, writable: bool):
    """(array, address) for repeated native calls on a long-lived buffer.

    The returned array holds a buffer-protocol export: the underlying
    memoryview/shm segment cannot release while it is referenced, which is
    what makes a CACHED address safe to pass to native code. Owners must drop
    the pin before closing the buffer (close paths retry on BufferError for
    the in-flight-call window).

    ``__array_interface__`` instead of ``.ctypes.data``: the latter constructs
    a ctypes helper object per access, measurable on the per-RPC path."""
    arr = np.frombuffer(buf, dtype=np.uint8)
    if writable and not arr.flags.writeable:
        raise ValueError("writable buffer required")
    return arr, arr.__array_interface__["data"][0]


def reset_for_tests() -> None:
    global _LIB, _SPIN, _TRIED
    _LIB = None
    _SPIN = None
    _TRIED = False
