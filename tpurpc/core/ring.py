"""The credit-managed, header/footer-framed receive ring — heart of the data plane.

This is a reimplementation of the *math* of the reference's
``src/core/lib/ibverbs/ring_buffer.{h,cc}`` (``RingBufferPollable``), not its code:

* Wire format per message (``ring_buffer.h:43-52``)::

      [8B header = payload byte count][payload, zero-padded to 8B][8B footer = all-ones]

  All fields start 8-byte aligned, and the ring capacity is a power of two ≥ 64, so no
  64-bit word ever straddles the wrap point.

* **Completion detection** — deliberately different from the reference
  (``ring_buffer.cc:56-97``). The reference keeps the consumed region zero (the reader
  memsets every byte it eats, ``ring_buffer.cc:122-191``) so that "header word ≠ 0"
  means "message starts here"; that zeroing is a full extra memory pass over all
  traffic. tpurpc stamps each message with the ring's monotone sequence number
  instead: header = ``[u32 len | u32 seq32]``, footer = ``seq64 ^ SALT``.  A message
  is complete iff the header's seq32 matches the reader's expected sequence AND the
  footer carries the expected 64-bit stamp — 96 bits of freshness, so stale bytes
  from previous wraps are self-evidently stale and nothing is ever zeroed.  The
  producer still writes payload → footer → header with a release fence before the
  header store (the reference gets the same guarantee from the NIC's in-order
  placement of a single RDMA WRITE).

* **Partial reads** (``ring_buffer.cc:122-191``, ``remain_``/``moving_head_``): a reader
  may drain fewer bytes than a message holds; progress is carried across calls, and the
  span is only zeroed + the head only advanced when the message is fully consumed.

* **Wrap-split writes** (``ring_buffer.cc:261-330``, ``GetWriteRequests``): one logical
  message occupies one contiguous span of ring offsets, which maps to ≤2 physical
  segments (split at the wrap).  ``RingWriter`` emits the same ≤2-segment descriptors;
  in the loopback transport they become memcpys, in a verbs transport they would be the
  SGE lists of an ``IBV_WR_RDMA_WRITE``, in the TPU transport they become device DMAs.

* **Credit flow control** (``pair.cc:276-301``): the writer stalls when the mirrored
  ``remote_head`` says the ring is full (3×8B reserved, ``ring_buffer.h:185-189``); the
  reader publishes its head back to the writer after consuming ≥ half the ring.

Differences from the reference, on purpose: head/tail are monotonically increasing
64-bit counters masked on access (the reference stores masked offsets), which makes the
full/empty math race-free and assertable; and padding bytes are never written because
the consumed-region-is-zero invariant already guarantees they are zero.
"""

from __future__ import annotations

import ctypes
import struct
import time
from typing import Callable, List, Optional, Sequence, Tuple

from tpurpc.core import _native
from tpurpc.obs import flight as _flight
from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.tpu import ledger

# tpurpc-scope (ISSUE 4): hot counters are cached module-level objects —
# one GIL-atomic int add per DRAIN/BATCH, no lookup, no lock. Ring state
# (head/tail/credits) costs the hot path nothing: the fleet gauges read
# the attributes the ring already maintains, at scrape time only.
_MSGS_IN = _metrics.counter("ring_msgs_read")
_BYTES_IN = _metrics.counter("ring_bytes_read")
_MSGS_OUT = _metrics.counter("ring_msgs_written")
_BYTES_OUT = _metrics.counter("ring_bytes_written")
_READERS = _metrics.fleet("ring_credit_unpublished_bytes",
                          lambda r: r.consumed_since_publish)
_WRITERS = _metrics.fleet("ring_in_flight_bytes",
                          lambda w: w.tail - w.remote_head)

# tpurpc-lens (ISSUE 8): byte-flow waterfall hop counters (bytes / busy_ns
# / copy_bytes per batched op — ring bytes move by host memcpy, so every
# accounted byte is also a copy byte) + sampling-profiler frame markers.
_LENS_SR_BYTES, _LENS_SR_NS, _LENS_SR_COPY = _lens.hop_counters("send_ring")
_LENS_PR_BYTES, _LENS_PR_NS, _LENS_PR_COPY = _lens.hop_counters("peer_ring")

_LENS_STAGES = {
    "write": "ring-write",
    "writev": "ring-write",
    "write_many": "ring-write",
    "_writev_native": "ring-write",
    "read": "ring-read",
    "read_into": "ring-read",
    "_read_into_native": "ring-read",
    "drain_into": "ring-read",
    "read_many": "ring-read",
    "scan_complete": "ring-read",
}
_profiler.register_stages(__file__, _LENS_STAGES)

ALIGN = 8
HEADER_BYTES = 8
FOOTER_BYTES = 8
#: Salt in the footer stamp (must match native/src/ring.cc kFooterSalt).
FOOTER_SALT = 0xA5C3F00D5EEDFACE
#: Reserved slack the writer never fills: header + footer + one 8B gap
#: (``ring_buffer.h:185-189`` reserves the same 3×8B).
RESERVED_BYTES = HEADER_BYTES + FOOTER_BYTES + ALIGN

_U64 = struct.Struct("<Q")
_U64_MASK = (1 << 64) - 1


def footer_stamp(seq: int) -> int:
    return (seq ^ FOOTER_SALT) & _U64_MASK


def header_stamp(length: int, seq: int) -> int:
    return (length & 0xFFFFFFFF) | ((seq & 0xFFFFFFFF) << 32)


def align_up(n: int) -> int:
    return (n + ALIGN - 1) & ~(ALIGN - 1)


def truncate_after_read(buf: bytearray, n: int) -> None:
    """``del buf[n:]`` with a bounded BufferError retry.

    The reader frames that just filled ``buf`` exported memoryviews over
    it, and a frame that has RETURNED can be kept alive for a sub-
    millisecond window by anything iterating ``sys._current_frames`` —
    notably the tpurpc-lens sampling profiler (a held frame object keeps
    its locals, exports included, until the holder drops it). An in-place
    resize racing that window raises BufferError; retrying for a few
    milliseconds is the same trade ``RingReader.release`` makes for the
    GIL-free spin. The final attempt re-raises honestly."""
    import time as _t

    for _ in range(200):
        try:
            del buf[n:]
            return
        except BufferError:
            _t.sleep(0.0001)
    del buf[n:]


def message_span(payload_len: int) -> int:
    """Total ring bytes one message of ``payload_len`` occupies."""
    return HEADER_BYTES + align_up(payload_len) + FOOTER_BYTES


class RingLayout:
    """Pure offset math shared by every transport (host shm, native, TPU staging)."""

    __slots__ = ("capacity", "mask")

    def __init__(self, capacity: int):
        if capacity < 64 or capacity & (capacity - 1):
            # ring_buffer.cc:22 asserts power-of-two capacity.
            raise ValueError(f"ring capacity must be a power of two >= 64, got {capacity}")
        self.capacity = capacity
        self.mask = capacity - 1

    def phys(self, abs_off: int) -> int:
        return abs_off & self.mask

    def max_payload(self) -> int:
        """Largest single-message payload this ring can ever carry."""
        return self.capacity - RESERVED_BYTES

    def segments(self, abs_off: int, nbytes: int) -> List[Tuple[int, int]]:
        """Map a contiguous logical span to ≤2 physical (offset, len) segments.

        The reference's ``GetWriteRequests`` (``ring_buffer.cc:261-330``) does the same
        split to build ≤2 ``ibv_send_wr``s.
        """
        assert 0 <= nbytes <= self.capacity
        if nbytes == 0:
            return []
        p = self.phys(abs_off)
        if p + nbytes <= self.capacity:
            return [(p, nbytes)]
        first = self.capacity - p
        return [(p, first), (0, nbytes - first)]


class RingReader:
    """Consumer view over the ring memory this side owns (the peer writes into it)."""

    def __init__(self, buf, capacity: Optional[int] = None):
        self.buf = memoryview(buf)
        cap = capacity if capacity is not None else len(self.buf)
        if len(self.buf) < cap:
            raise ValueError("buffer smaller than declared capacity")
        self.layout = RingLayout(cap)
        self.head = 0  # absolute; phys offset = head & mask
        self.seq = 0   # sequence expected of the next unparsed message
        # Partial-read state (reference remain_/moving_head_, ring_buffer.cc:168-183).
        self._msg_len = 0        # payload length of the in-progress message (0 = none)
        self._msg_read = 0       # payload bytes already handed to the app
        # Credit state (pair.cc:276-284: publish after consuming >= half ring).
        self.consumed_since_publish = 0
        # Native fast path: scan/copy/zero in C++ when the lib is built and the
        # ring memory is addressable (shm/local buffers always are). The pin
        # (a live np view) is what keeps the cached address valid: the ring
        # cannot unmap while it exists; release() drops it first.
        self._nat = _native.load()
        self._nat_addr = None
        self._nat_pin = None
        if self._nat is not None:
            try:
                self._nat_pin, self._nat_addr = _native.pin(
                    self.buf, writable=True)
            except (ValueError, TypeError):
                self._nat = None
        _READERS.track(self)

    # -- completion scanning ------------------------------------------------

    def _word(self, abs_off: int) -> int:
        p = self.layout.phys(abs_off)
        return _U64.unpack_from(self.buf, p)[0]

    def _message_at(self, abs_off: int, seq: int) -> int:
        """Payload length of the complete message stamped ``seq`` starting at
        abs_off, else 0.

        Role of ``HasMessage``/``GetReadableSize`` (``ring_buffer.cc:56-97``),
        reworked for sequence-stamped framing: complete iff the header's
        seq32 matches AND the footer carries the 64-bit stamp (see module
        docstring)."""
        hdr = self._word(abs_off)
        if (hdr >> 32) != (seq & 0xFFFFFFFF):
            return 0  # stale bytes or header not yet placed
        ln = hdr & 0xFFFFFFFF
        if ln == 0 or ln > self.layout.max_payload():
            # Stale lookalike, not corruption: zeros (fresh ring / zero
            # payloads) match any seq ≡ 0 mod 2^32, and after the 32-bit
            # stamp laps, old payload bytes may transiently mimic a header.
            # The 64-bit footer stamp still gates completion.
            return 0
        footer_off = abs_off + HEADER_BYTES + align_up(ln)
        if self._word(footer_off) != footer_stamp(seq):
            return 0  # body still in flight
        return ln

    def _alive(self) -> bool:
        """buf still mapped? (GIL held from here through the native call, so a
        racing release() cannot interleave — see PyDLL note in _native.py)"""
        try:
            _ = self.buf.nbytes
            return True
        except ValueError:
            return False

    def has_message(self) -> bool:
        if self._msg_len:
            return True
        if self._nat is not None:
            if not self._alive():
                raise RingCorruption("ring memory released")
            r = self._nat.tpr_ring_has_message(
                self._nat_addr, self.layout.capacity, self.head,
                self._msg_len, self.seq)
            if r < 0:
                raise RingCorruption(
                    f"invalid header length at offset "
                    f"{self.layout.phys(self.head)}")
            return bool(r)
        return self._message_at(self.head, self.seq) != 0

    def readable(self) -> int:
        """Total payload bytes currently drainable (all complete messages).

        Like ``GetReadableSize`` the endpoint uses to size its slice allocation
        (``rdma_bp_posix.cc:306-327`` → ``ring_buffer.cc:67-97``).
        """
        if self._nat is not None:
            if not self._alive():
                raise RingCorruption("ring memory released")
            return self._nat.tpr_ring_readable(
                self._nat_addr, self.layout.capacity, self.head,
                self._msg_len, self._msg_read, self.seq)
        total = 0
        off = self.head
        seq = self.seq
        if self._msg_len:  # in-progress message carries seq; next one is seq+1
            total += self._msg_len - self._msg_read
            off += message_span(self._msg_len)
            seq += 1
        scanned = 0
        while scanned < self.layout.capacity:
            ln = self._message_at(off, seq)
            if ln == 0:
                break
            total += ln
            span = message_span(ln)
            off += span
            scanned += span
            seq += 1
        return total

    # -- draining -----------------------------------------------------------

    def _copy_out(self, abs_off: int, n: int, dst: memoryview, dst_off: int) -> None:
        for seg_off, seg_len in self.layout.segments(abs_off, n):
            dst[dst_off:dst_off + seg_len] = self.buf[seg_off:seg_off + seg_len]
            dst_off += seg_len

    def read_into(self, dst) -> int:
        """Drain up to ``len(dst)`` payload bytes; returns the count actually read.

        Handles message-at-a-time consumption and partial-message resumption
        (``ring_buffer.cc:122-191``). Unlike the reference, consumed spans are
        NOT zeroed — freshness comes from the sequence stamps (module
        docstring), saving a full memory pass per byte of traffic.
        """
        dst = memoryview(dst)
        if dst.readonly:
            raise ValueError("read_into needs a writable buffer")
        dst = dst.cast("B")
        if self._nat is not None and len(dst) > 0:
            return self._read_into_native(dst)
        total = 0
        seq0 = self.seq
        t0 = time.monotonic_ns()
        while total < len(dst):
            if self._msg_len == 0:
                ln = self._message_at(self.head, self.seq)
                if ln == 0:
                    break
                self._msg_len = ln
                self._msg_read = 0
            n = min(len(dst) - total, self._msg_len - self._msg_read)
            payload_off = self.head + HEADER_BYTES + self._msg_read
            self._copy_out(payload_off, n, dst, total)
            self._msg_read += n
            total += n
            if self._msg_read == self._msg_len:
                span = message_span(self._msg_len)
                self.head += span
                self.consumed_since_publish += span
                self._msg_len = 0
                self._msg_read = 0
                self.seq += 1
        dt = time.monotonic_ns() - t0
        ledger.host_copy(total)
        _MSGS_IN.inc(self.seq - seq0)
        _BYTES_IN.inc(total)
        _LENS_PR_BYTES.inc(total)
        _LENS_PR_NS.inc(dt)
        _LENS_PR_COPY.inc(total)
        return total

    def _read_into_native(self, dst: memoryview) -> int:
        if not self._alive():
            raise RingCorruption("ring memory released")
        head = ctypes.c_uint64(self.head)
        msg_len = ctypes.c_uint64(self._msg_len)
        msg_read = ctypes.c_uint64(self._msg_read)
        consumed = ctypes.c_uint64(self.consumed_since_publish)
        seq0 = self.seq
        seq = ctypes.c_uint64(self.seq)
        t0 = time.monotonic_ns()
        n = self._nat.tpr_ring_read_into(
            self._nat_addr, self.layout.capacity,
            ctypes.byref(head), ctypes.byref(msg_len), ctypes.byref(msg_read),
            _native.addr_of(dst, writable=True), len(dst),
            ctypes.byref(consumed), ctypes.byref(seq))
        dt = time.monotonic_ns() - t0
        if n == 0xFFFFFFFFFFFFFFFF:
            raise RingCorruption(
                f"invalid header length at offset "
                f"{self.layout.phys(head.value)}")
        self.head = head.value
        self._msg_len = msg_len.value
        self._msg_read = msg_read.value
        self.consumed_since_publish = consumed.value
        self.seq = seq.value
        ledger.host_copy(n)
        _MSGS_IN.inc(self.seq - seq0)
        _BYTES_IN.inc(n)
        _LENS_PR_BYTES.inc(n)
        _LENS_PR_NS.inc(dt)
        _LENS_PR_COPY.inc(n)
        return n

    def read(self, nbytes: int) -> bytes:
        # Size by capacity, not by a readable() pre-scan — readable() re-parses every
        # queued message's framing, and read_into() is about to do that walk anyway.
        out = bytearray(min(nbytes, self.layout.capacity))
        n = self.read_into(out)
        truncate_after_read(out, n)  # in place: bytes(out[:n]) copies twice
        return bytes(out)

    # -- batched draining -----------------------------------------------------

    def scan_complete(self, max_msgs: Optional[int] = None,
                      max_bytes: Optional[int] = None
                      ) -> Tuple[List[Tuple[int, int]], int]:
        """One scan pass: descriptors of the complete messages queued at head.

        Returns ``(descs, span)`` — ``descs`` is ``[(abs_msg_off, payload_len),
        ...]`` in arrival order, ``span`` the total ring bytes they occupy.
        Stops at the first incomplete message, at ``max_msgs`` descriptors, or
        once accepting another message would push total payload past
        ``max_bytes``. Requires no partial read in progress (``_msg_len == 0``)
        — partial resumption stays on :meth:`read_into`.
        """
        assert self._msg_len == 0, "scan_complete during a partial read"
        descs: List[Tuple[int, int]] = []
        span = 0
        payload = 0
        off = self.head
        seq = self.seq
        while span < self.layout.capacity:
            if max_msgs is not None and len(descs) >= max_msgs:
                break
            ln = self._message_at(off, seq)
            if ln == 0:
                break
            if max_bytes is not None and descs and payload + ln > max_bytes:
                break
            descs.append((off, ln))
            s = message_span(ln)
            off += s
            span += s
            payload += ln
            seq += 1
        return descs, span

    def drain_into(self, dst) -> Tuple[int, int]:
        """Batched :meth:`read_into`: same bytes, same partial-message
        semantics, but head/seq/credit state and the copy ledger are updated
        ONCE per call instead of once per message, and the whole batch is
        planned in a single framing scan.  Returns ``(payload_bytes,
        completed_messages)`` — the tentpole primitive of the batched receive
        pipeline (one wakeup → one drain → many messages).

        The native path is already a single C call per batch
        (``tpr_ring_read_into`` drains everything that fits); there the
        message count falls out of the sequence-stamp delta.
        """
        dst = memoryview(dst)
        if dst.readonly:
            raise ValueError("drain_into needs a writable buffer")
        dst = dst.cast("B")
        if self._nat is not None and len(dst) > 0:
            seq0 = self.seq
            n = self._read_into_native(dst)
            return n, self.seq - seq0
        total = 0
        nmsgs = 0
        head = self.head
        seq = self.seq
        msg_len = self._msg_len
        msg_read = self._msg_read
        t0 = time.monotonic_ns()
        while total < len(dst):
            if msg_len == 0:
                ln = self._message_at(head, seq)
                if ln == 0:
                    break
                msg_len = ln
                msg_read = 0
            n = min(len(dst) - total, msg_len - msg_read)
            self._copy_out(head + HEADER_BYTES + msg_read, n, dst, total)
            msg_read += n
            total += n
            if msg_read == msg_len:
                head += message_span(msg_len)
                msg_len = 0
                msg_read = 0
                seq += 1
                nmsgs += 1
        # publish the whole batch's progress once
        dt = time.monotonic_ns() - t0
        self.consumed_since_publish += head - self.head
        self.head = head
        self.seq = seq
        self._msg_len = msg_len
        self._msg_read = msg_read
        ledger.host_copy(total)
        _MSGS_IN.inc(nmsgs)
        _BYTES_IN.inc(total)
        _LENS_PR_BYTES.inc(total)
        _LENS_PR_NS.inc(dt)
        _LENS_PR_COPY.inc(total)
        return total, nmsgs

    def read_many(self, max_msgs: Optional[int] = None,
                  max_bytes: Optional[int] = None) -> List[memoryview]:
        """Drain every complete message in ONE segmented copy-out.

        The batch's whole ring span (headers, payloads, footers) is copied
        into one fresh buffer with at most 2 ``memoryview`` copies (the split
        at the wrap point); per-message payloads come back as zero-copy views
        over that buffer, and head/seq publish once for the batch.  Returns
        ``[]`` when nothing is complete or a partial read is in progress
        (resume that via :meth:`read_into` first).

        Callers own the backing buffer through the returned views — the ring
        span is released (head advanced) before this returns, so the views
        never alias ring memory.
        """
        if self._msg_len:
            return []
        if max_bytes is None:
            max_bytes = self.layout.capacity
        t0 = time.monotonic_ns()
        descs, span = self.scan_complete(max_msgs, max_bytes)
        if not descs:
            return []
        scratch = memoryview(bytearray(span))
        base = self.head
        dst_off = 0
        for seg_off, seg_len in self.layout.segments(base, span):
            scratch[dst_off:dst_off + seg_len] = self.buf[seg_off:seg_off + seg_len]
            dst_off += seg_len
        out = [scratch[off - base + HEADER_BYTES:
                       off - base + HEADER_BYTES + ln] for off, ln in descs]
        dt = time.monotonic_ns() - t0
        self.head = base + span
        self.seq += len(descs)
        self.consumed_since_publish += span
        payload = sum(ln for _off, ln in descs)
        ledger.host_copy(span)
        _MSGS_IN.inc(len(descs))
        _BYTES_IN.inc(payload)
        _LENS_PR_BYTES.inc(payload)
        _LENS_PR_NS.inc(dt)
        _LENS_PR_COPY.inc(payload)
        return out

    # -- credits ------------------------------------------------------------

    #: Credit-publish threshold divisor. The reference publishes after half
    #: the ring (``pair.cc:276-284``) because each credit return is an RDMA
    #: write worth amortizing; tpurpc's credit is an 8-byte shm store + one
    #: token, so finer quanta (capacity/4) buy pipelining — the stalled
    #: writer resumes while the reader still drains — at negligible cost.
    PUBLISH_DIVISOR = 4

    def should_publish_head(self) -> bool:
        """True once capacity/PUBLISH_DIVISOR has been consumed since the
        last publish (the reference's credit-return rule, ``pair.cc:276-284``,
        with a finer default quantum — see PUBLISH_DIVISOR)."""
        return (self.consumed_since_publish
                >= self.layout.capacity // self.PUBLISH_DIVISOR)

    def take_publish(self) -> int:
        """Consume the pending credit and return the head value to publish."""
        self.consumed_since_publish = 0
        return self.head

    # -- invariants ---------------------------------------------------------

    def release(self) -> None:
        """Drop the memoryview so the underlying region (e.g. POSIX shm) can
        close. Retries: a GIL-free spin (Pair.spin) may hold an export for ≤
        one bounded slice."""
        import time

        self._nat_pin = None  # drop our own export before releasing
        deadline = time.monotonic() + 2.0
        while True:
            try:
                self.buf.release()
                return
            except BufferError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.001)

    def check_empty_region(self) -> bool:
        """Debug invariant (role of ``ring_buffer.h:215-219``'s check_empty,
        adapted to seq framing): if no message is pending, the header word at
        head must NOT already carry the expected sequence stamp with a bad
        body — i.e. the position is either stale bytes or a complete message."""
        if self._msg_len != 0 or self.has_message():
            return True
        return (self._word(self.head) >> 32) != (self.seq & 0xFFFFFFFF)


class RingCorruption(RuntimeError):
    """A framing invariant was violated (footer/header asserts in ring_buffer.cc)."""


WriteFn = Callable[[int, "memoryview | bytes"], None]


class RingWriter:
    """Producer view: encodes messages into the *peer's* ring via one-sided writes.

    ``write_fn(phys_offset, data)`` performs the actual placement — a memcpy for the
    loopback/shm transport, an RDMA WRITE SGE for verbs, a DMA for the TPU path.  The
    writer never reads the peer ring; everything it knows about the consumer arrives via
    ``update_remote_head`` (the credit write, mirroring ``status_report.remote_head``,
    ``pair.h:100-103`` / ``pair.cc:294-301``).
    """

    def __init__(self, capacity: int, write_fn: WriteFn,
                 mapped: Optional[memoryview] = None):
        self.layout = RingLayout(capacity)
        self.write_fn = write_fn
        self.tail = 0         # absolute count of ring bytes ever written
        self.seq = 0          # sequence stamp of the next message
        self.remote_head = 0  # mirrored consumer head (credits)
        #: tpurpc-blackbox: owner-assigned flight tag + the open credit-
        #: starvation edge — emission is edge-triggered (one event per
        #: starve episode, one per recovery), never per message
        self.flight_tag = 0
        self._starved = False
        # Native gather-encode straight into the mapped peer ring (shm window);
        # transports whose placement is a callback (TPU DMA) stay on write_fn.
        self._nat = _native.load() if mapped is not None else None
        self._nat_addr = None
        if self._nat is not None:
            try:
                self._nat_addr = _native.addr_of(mapped, writable=True)
                self._mapped = mapped  # keep the exporter alive
            except (ValueError, TypeError):
                self._nat = None
        _WRITERS.track(self)

    # -- flow control -------------------------------------------------------

    def in_flight(self) -> int:
        used = self.tail - self.remote_head
        assert 0 <= used <= self.layout.capacity, (self.tail, self.remote_head)
        return used

    def writable_payload(self) -> int:
        """Largest payload acceptable to :meth:`write` right now.

        ``capacity - used - 3×8B``; because this value is 8-aligned, any payload ≤ it
        has ``span(payload) ≤ capacity - used - 8``, i.e. the 8-byte gap before the
        consumer's head is never touched.  (Reference: ``GetWritableSize``,
        ``ring_buffer.h:185-189``.)
        """
        return max(0, self.layout.capacity - self.in_flight() - RESERVED_BYTES)

    def update_remote_head(self, head: int) -> None:
        if head < self.remote_head or head > self.tail:
            raise RingCorruption(
                f"credit head {head} outside [{self.remote_head}, {self.tail}]")
        self.remote_head = head

    # -- encoding -----------------------------------------------------------

    def _put(self, abs_off: int, data) -> None:
        view = memoryview(data).cast("B")
        pos = 0
        for seg_off, seg_len in self.layout.segments(abs_off, len(view)):
            self.write_fn(seg_off, view[pos:pos + seg_len])
            pos += seg_len

    def write(self, payload) -> int:
        """Encode one message; returns payload bytes written (all or nothing).

        Caller is responsible for chunking to :meth:`writable_payload` — the pair layer
        does that, mirroring the reference's partial-send resumption
        (``pair.cc:645-734``).
        """
        return self.writev([payload])

    def writev(self, slices: Sequence) -> int:
        """Gather-encode several slices as ONE message (one header/footer), like the
        reference's ``grpc_slice*`` gather send building a single doorbell
        (``pair.cc:645-734``) and ``EncodeBuffer`` iovec variants
        (``ring_buffer.h:106-178``)."""
        views = [memoryview(s).cast("B") for s in slices]
        payload_len = sum(len(v) for v in views)
        if payload_len == 0:
            return 0
        if payload_len > self.writable_payload():
            if not self._starved:
                self._starved = True
                _flight.emit(_flight.CREDIT_STARVE_BEGIN, self.flight_tag,
                             self.tail - self.remote_head)
            raise RingFull(payload_len, self.writable_payload())
        if self._starved:
            self._starved = False
            _flight.emit(_flight.CREDIT_STARVE_END, self.flight_tag)
        if self._nat is not None:
            return self._writev_native(views, payload_len)
        # Order matters for lock-free completion detection: payload, footer, header.
        t0 = time.monotonic_ns()
        ledger.host_copy(payload_len)
        off = self.tail + HEADER_BYTES
        for v in views:
            self._put(off, v)
            off += len(v)
        # Padding bytes are never validated — no need to write them.
        footer_off = self.tail + HEADER_BYTES + align_up(payload_len)
        self._put(footer_off, _U64.pack(footer_stamp(self.seq)))
        self._put(self.tail, _U64.pack(header_stamp(payload_len, self.seq)))
        dt = time.monotonic_ns() - t0
        self.tail += message_span(payload_len)
        self.seq += 1
        _MSGS_OUT.inc()
        _BYTES_OUT.inc(payload_len)
        _LENS_SR_BYTES.inc(payload_len)
        _LENS_SR_NS.inc(dt)
        _LENS_SR_COPY.inc(payload_len)
        return payload_len


    def write_many(self, payloads: Sequence) -> Tuple[int, int]:
        """Encode a BATCH of messages with one bulk placement.

        ``payloads`` is a sequence of messages; each message is a bytes-like
        or a gather list of segments.  As many whole messages as current
        credits allow are framed into one scratch image — payloads, padding
        and footers — which lands in the peer ring as a single contiguous
        span (≤2 ``write_fn`` segments at the wrap), followed by one 8-byte
        header store per message.  The headers are the completion gates and
        must become visible AFTER their payload+footer bytes, which the bulk
        copy cannot order internally; everything else is one writev-style
        placement instead of 3 stores per message.

        Returns ``(messages_written, payload_bytes_written)``; messages are
        all-or-nothing, in order, and the caller re-arms on credits for the
        rest (same contract as :meth:`write`).
        """
        views_per_msg: List[List[memoryview]] = []
        lens: List[int] = []
        # Each accepted message shrinks the remaining writable payload by its
        # whole span (writable_payload's 8-aligned invariant holds per
        # message inductively: budget' = budget - span keeps the 8-byte gap
        # before the consumer's head untouched for every prefix).
        budget = self.writable_payload()
        rejected = False
        for p in payloads:
            segs = ([memoryview(s).cast("B") for s in p]
                    if isinstance(p, (list, tuple))
                    else [memoryview(p).cast("B")])
            ln = sum(len(v) for v in segs)
            if ln == 0:
                continue
            if ln > budget:
                rejected = True
                break
            views_per_msg.append(segs)
            lens.append(ln)
            budget -= message_span(ln)
        if not views_per_msg:
            if rejected and not self._starved:
                # offered messages, accepted none: the writer is credit-
                # starved (edge event; write resumption clears it)
                self._starved = True
                _flight.emit(_flight.CREDIT_STARVE_BEGIN, self.flight_tag,
                             self.tail - self.remote_head)
            return 0, 0
        if self._starved:
            self._starved = False
            _flight.emit(_flight.CREDIT_STARVE_END, self.flight_tag)
        if len(views_per_msg) == 1:
            return 1, self.writev(views_per_msg[0])
        t0 = time.monotonic_ns()
        total_span = sum(message_span(ln) for ln in lens)
        scratch = memoryview(bytearray(total_span))
        rel = 0
        seq = self.seq
        for segs, ln in zip(views_per_msg, lens):
            pos = rel + HEADER_BYTES
            for v in segs:
                scratch[pos:pos + len(v)] = v
                pos += len(v)
            footer_rel = rel + HEADER_BYTES + align_up(ln)
            scratch[footer_rel:footer_rel + 8] = _U64.pack(footer_stamp(seq))
            # header word stays zero in the image — placed individually below
            rel += message_span(ln)
            seq += 1
        # one bulk placement: payloads + padding + footers, headers zeroed
        self._put(self.tail, scratch)
        # completion gates, in order, AFTER the bulk copy landed
        rel = 0
        seq = self.seq
        for ln in lens:
            self._put(self.tail + rel, _U64.pack(header_stamp(ln, seq)))
            rel += message_span(ln)
            seq += 1
        dt = time.monotonic_ns() - t0
        payload_total = sum(lens)
        ledger.host_copy(payload_total)
        self.tail += total_span
        self.seq = seq
        _MSGS_OUT.inc(len(lens))
        _BYTES_OUT.inc(payload_total)
        _LENS_SR_BYTES.inc(payload_total)
        _LENS_SR_NS.inc(dt)
        _LENS_SR_COPY.inc(payload_total)
        return len(lens), payload_total

    def _writev_native(self, views: Sequence[memoryview],
                       payload_len: int) -> int:
        try:
            _ = self._mapped.nbytes  # peer window still mapped? (see _alive)
        except ValueError:
            raise RingCorruption("peer ring window released") from None
        n = len(views)
        seg_ptrs = (ctypes.c_void_p * n)(
            *[_native.addr_of(v, writable=False) for v in views])
        seg_lens = (ctypes.c_uint64 * n)(*[len(v) for v in views])
        tail = ctypes.c_uint64(self.tail)
        seq = ctypes.c_uint64(self.seq)
        t0 = time.monotonic_ns()
        got = self._nat.tpr_ring_writev(
            self._nat_addr, self.layout.capacity, ctypes.byref(tail),
            self.remote_head, seg_ptrs, seg_lens, n, ctypes.byref(seq))
        dt = time.monotonic_ns() - t0
        if got == 0xFFFFFFFFFFFFFFFF:
            raise RingFull(payload_len, self.writable_payload())
        self.tail = tail.value
        self.seq = seq.value
        ledger.host_copy(got)
        _MSGS_OUT.inc()
        _BYTES_OUT.inc(got)
        _LENS_SR_BYTES.inc(got)
        _LENS_SR_NS.inc(dt)
        _LENS_SR_COPY.inc(got)
        return got


class RingFull(RuntimeError):
    """Message does not fit the currently writable span; caller must wait for credits."""

    def __init__(self, wanted: int, available: int):
        super().__init__(f"ring full: wanted {wanted} payload bytes, {available} writable")
        self.wanted = wanted
        self.available = available
