"""tpurpc-express: one-sided rendezvous transfers for bulk tensor payloads.

The paper's real thesis ("RPC Considered Harmful", arXiv:1805.08430) is that
large DL tensors should not ride the framed request/response path at all:
chunked ring framing pays per-chunk credit handshakes, per-fragment headers,
and a receive-side landing copy for every payload byte. This module moves
any payload over a size bar the way the reference moves every payload —
as ONE one-sided write into a peer-advertised registered landing region
(RDMAbox, arXiv:2104.12197: merged writes into pre-registered regions) —
while the framed RPC carries only a small offer/claim/complete control
exchange:

    sender                                  receiver
    ------                                  --------
    OFFER(req, nbytes, kinds)  ──────────►  lease landing region from pool
                               ◄──────────  CLAIM(req, lease, region descr)
    one-sided write of every
    gather segment into the
    region (RDMA WRITE on the
    verbs domain; ONE memoryview
    copy on shm/local/tcp_window)
    COMPLETE(lease, nbytes, flags) ───────► deliver region view zero-copy
                                            (decode aliases it in place)

Every control message rides the existing framed connection, so ordering
with interleaved small MESSAGEs is free (frame arrival order), and a peer
that never negotiated the capability never sees an unknown frame.

Protocol invariants (modeled exhaustively in ``analysis/ringcheck.py
check_rendezvous``; mutants ``write_before_claim`` and
``complete_before_write`` are both killed):

* the sender writes a region only between CLAIM and COMPLETE/RELEASE;
* a region is reused only after COMPLETE (and, in this emulation, after
  every consumer alias died — the pool's weakref-finalize recycling) or
  after an explicit RELEASE;
* peer death with a claimed region releases it (``RdvLink.close``).

Steady-state fast path: after each completed transfer the receiver
PRE-GRANTS a fresh claim of the same size class (req id 0), so a stream of
same-shaped tensors pays zero claim round trips — the RDMAbox
pre-registered-buffer discipline. Pre-granted transfers emit no flight
events (edges, not traffic); solicited offers/claims/releases do, which is
exactly the evidence the stall watchdog's ``rendezvous`` stage reads.

Lifetime/recycling: a delivered payload is a numpy wrapper over the landing
region. Every downstream alias — codec decode views, 64B-aligned dlpack
imports into jax.Arrays — transitively references the wrapper, so a
``weakref.finalize`` on it is a sound "no consumer can observe this memory"
signal; only then does the region return to the pool's free list. Consumers
that copy simply never pin.

Env knobs: ``TPURPC_RENDEZVOUS`` (default on), ``TPURPC_RENDEZVOUS_MIN_KB``
(size bar, default 256 — bench ``stream_by_size`` measures the crossover),
``TPURPC_RENDEZVOUS_POOL_MB`` (landing pool budget per domain, default 256),
``TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S`` (claim wait before falling back to
the framed path, default 5).
"""

from __future__ import annotations

import itertools
import os
import struct
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpurpc.analysis.locks import make_condition, make_lock
from tpurpc.core import pair as _pair
from tpurpc.core import transport as _transport
from tpurpc.obs import flight as _flight
from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.tpu import ledger as _ledger
from tpurpc.utils import stats as _stats

__all__ = [
    "LandingPool", "RegionLease", "RdvLink", "landing_pool",
    "link_for_endpoint", "enabled", "min_bytes", "size_class",
    "OP_OFFER", "OP_CLAIM", "OP_COMPLETE", "OP_RELEASE", "HELLO_PAYLOAD",
    "BlockGrant", "GrantWriter",
]

# tpurpc-lens: the one-sided bulk write is its own waterfall hop — the
# bytes that no longer flow through wire/send_ring show up here
_LENS_RDV_BYTES, _LENS_RDV_NS, _LENS_RDV_COPY = _lens.hop_counters(
    "rendezvous")

_LENS_STAGES = {
    "send_message": "rendezvous",
    "_rdv_write": "rendezvous",
    "rdv_claim": "rendezvous",
    "on_offer": "rendezvous",
    "on_complete": "rendezvous",
}
_profiler.register_stages(__file__, _LENS_STAGES)

#: transfers negotiated / completed / fallen back — the ops-facing truth of
#: whether the bulk plane is actually carrying traffic
_RDV_SENT = _metrics.counter("rdv_transfers_sent")
_RDV_RECV = _metrics.counter("rdv_transfers_received")
_RDV_FALLBACK = _metrics.counter("rdv_fallbacks")
_RDV_REFUSED = _metrics.counter("rdv_claims_refused")
#: control ops that rode the FRAMED path (tpurpc-pulse: a descriptor-ring
#: link in steady state holds this flat — the ctrlring smoke and bench's
#: ctrl_wakeups_per_msg both read it as the zero-control-frames proof)
_RDV_CTRL_FRAMES = _metrics.counter("rdv_ctrl_frames")

# tpurpc-pulse: framed control sends are control-plane busy time — same
# hop as the descriptor-ring posts/drains in core/ctrlring.py, so the
# waterfall shows the whole control plane's busy share in one row
_LENS_CTRL_BYTES, _LENS_CTRL_NS, _LENS_CTRL_COPY = _lens.hop_counters(
    "ctrl")

# -- control ops (canonical small ints; each wire plane maps them onto its
#    own frame vocabulary — frame.py types 8..11, h2 extension-frame flags)
OP_OFFER = 1
OP_CLAIM = 2
OP_COMPLETE = 3
OP_RELEASE = 4

#: capability hello for the native framing plane: a PING with this payload.
#: Any compliant peer (including the C plane and older builds) just echoes
#: it in a PONG; only a rendezvous-capable peer ALSO recognizes it and
#: arms its link — so the negotiation is safe against every deployed peer.
HELLO_PAYLOAD = b"\x00tpurpc-rdv1"

_OFFER = struct.Struct("<QQ")       # req_id, nbytes (+ kinds utf8 tail)
_CLAIM_HDR = struct.Struct("<QQB")  # req_id, lease_id, ok
_CLAIM_REG = struct.Struct("<QQ16sB")  # offset, capacity, nonce, standing
_COMPLETE = struct.Struct("<QQB")   # lease_id, nbytes, flags
_RELEASE = struct.Struct("<QQ")     # lease_id (0 = none), req_id
_DOORBELL = struct.Struct("<Q")     # consumer-freed count (see below)

_MIN_CLASS = 64 * 1024
_ALIGN = 64
_NONCE_BYTES = 16
_MAX_TRANSFER = 1 << 30  # sanity bound on one offer
_WINDOW_CACHE = 64       # open peer-region windows kept per link
#: standing claims per (link, size class). Sized so a pipelined sender
#: (bounded stream-credit window) never waits a claim round trip in steady
#: state — misses re-pay ~0.8 ms on the 1-core rig (measured; 18/64
#: messages missed at depth 2, zero at 4).
_PREGRANT_DEPTH = 4

_SENTINEL_PENDING = object()
_SENTINEL_REFUSED = object()

#: test seams (tests/test_chaos.py, tools/rendezvous_smoke.py): a receiver
#: with drop_offers set ignores OFFERs entirely (claim-starved sender); a
#: sender with wedge_after_claim set blocks there until the event fires or
#: the link dies (peer-death-mid-rendezvous chaos scenario)
TEST_HOOKS: Dict[str, object] = {}


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def enabled() -> bool:
    return _env("TPURPC_RENDEZVOUS", "1").lower() not in ("0", "off",
                                                          "false")


def min_bytes() -> int:
    """The size bar: payloads at or above it rendezvous, below it they keep
    today's framed path untouched. Read live (the bench A/B toggles it)."""
    try:
        return max(1, int(_env("TPURPC_RENDEZVOUS_MIN_KB", "256"))) * 1024
    except ValueError:
        return 256 * 1024


def _pool_budget() -> int:
    try:
        return max(1, int(_env("TPURPC_RENDEZVOUS_POOL_MB", "256"))) << 20
    except ValueError:
        return 256 << 20


def _claim_timeout() -> float:
    try:
        return float(_env("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", "5"))
    except ValueError:
        return 5.0


def size_class(nbytes: int) -> int:
    """Round a transfer size up to its pool size class (power of two,
    floor 64 KiB) — the granularity at which regions pool and pre-grants
    match."""
    if nbytes > _MAX_TRANSFER:
        raise ValueError(f"transfer of {nbytes} bytes exceeds the "
                         f"{_MAX_TRANSFER} rendezvous bound")
    c = _MIN_CLASS
    while c < nbytes:
        c <<= 1
    return c


# ---------------------------------------------------------------------------
# Landing pool: registered regions the receiver advertises.
# ---------------------------------------------------------------------------

class _PoolRegion:
    """One registered landing region: domain Region + the 64B alignment
    offset of its payload span + the anti-mixup nonce and the consumer-done
    DOORBELL word behind it (layout: ``[pad][payload cap][nonce 16]
    [doorbell 8]``)."""

    __slots__ = ("region", "offset", "capacity", "nonce")

    def __init__(self, region: _pair.Region, offset: int, capacity: int,
                 nonce: bytes):
        self.region = region
        self.offset = offset
        self.capacity = capacity
        self.nonce = nonce

    def doorbell_store(self, value: int) -> None:
        """Publish the consumer-freed count INTO the region, where the
        sender reads it through its already-open window — the zero-frame
        "this region is reusable" signal (RDMAbox's pre-registered-buffer
        discipline without a control message per transfer). Plain stores
        suffice on TSO hardware: the count is monotonic and the sender
        orders its payload write after the matching read by program order;
        non-view domains (verbs/tcp_window) never read it and stay on
        explicit grant frames."""
        _DOORBELL.pack_into(self.region.buf,
                            self.offset + self.capacity + _NONCE_BYTES,
                            value)


class RegionLease:
    """A pool region claimed for transfers on one link.

    Two lifetimes: a one-shot lease (solicited claim) delivers once and
    recycles when the delivered wrapper's last alias dies; a STANDING
    lease (``standing=True``, the steady-state grant) stays claimed across
    many transfers — after each delivery the wrapper's death rings the
    region's doorbell instead of recycling, and the sender reuses the
    region with no further control traffic."""

    __slots__ = ("pool", "pr", "lease_id", "cls", "kind", "pregrant",
                 "standing", "delivered", "_freed", "_retired", "_recycled",
                 "_discard", "_lock")

    #: lint rule `lock`: settlement state shared between the delivering
    #: reader thread, wrapper finalizers (whichever thread drops the last
    #: alias) and the link's death path
    _GUARDED_BY = {"delivered": "_lock", "_freed": "_lock",
                   "_retired": "_lock", "_recycled": "_lock",
                   "_discard": "_lock"}

    def __init__(self, pool: "LandingPool", pr: _PoolRegion, lease_id: int,
                 cls: int):
        self.pool = pool
        self.pr = pr
        self.lease_id = lease_id
        self.cls = cls
        self.kind = pool.kind
        self.pregrant = False
        self.standing = False
        self.delivered = 0
        self._freed = 0
        self._retired = False
        self._recycled = False
        self._discard = False
        self._lock = make_lock("RegionLease._lock")

    def _maybe_recycle_locked(self) -> bool:
        """The ONE recycle rule: a region returns to the pool exactly once,
        when no further delivery can happen (retired, or a one-shot lease
        already delivered) AND no delivered wrapper is still aliased."""
        if self._recycled:
            return False
        done = self._retired or (self.delivered > 0 and not self.standing)
        if done and self._freed == self.delivered:
            # contract: caller holds _lock (the _locked suffix)
            self._recycled = True  # tpr: allow(lock)
            return True
        return False

    def claim_fields(self) -> Tuple[str, str, int, int, bytes, bool]:
        pr = self.pr
        return (self.kind, pr.region.handle, pr.offset, pr.capacity,
                pr.nonce, self.standing)

    def deliver(self, nbytes: int):
        """The received payload as a writable buffer aliasing the region.
        Region reuse is gated on the wrapper's death: every consumer alias
        (decode views, aligned dlpack imports) transitively references it,
        so the finalize fires only when no consumer can observe the memory
        anymore — then a one-shot lease recycles to the pool and a
        standing lease rings the doorbell for the sender."""
        with self._lock:
            if self._retired or (self.delivered and not self.standing):
                raise RuntimeError("lease already settled")
            if nbytes > self.pr.capacity:
                raise ValueError(f"complete of {nbytes} exceeds leased "
                                 f"capacity {self.pr.capacity}")
            if self.standing and self.delivered != self._freed:
                # the sender reused a standing region before its previous
                # wrapper died — a protocol violation the doorbell exists
                # to prevent; refuse the delivery rather than hand out a
                # second alias over live memory
                raise RuntimeError("standing region completed while its "
                                   "previous delivery is still aliased")
            self.delivered += 1
            gen = self.delivered
        wrapper = np.frombuffer(self.pr.region.buf, np.uint8, count=nbytes,
                                offset=self.pr.offset)
        weakref.finalize(wrapper, self._on_wrapper_dead, gen)
        # hand out a memoryview OVER the wrapper (not the ndarray itself):
        # the stream layer treats message bodies as buffers (`body in
        # (sentinels)` must stay a scalar check), and every consumer alias
        # still chains to the wrapper, so the finalize stays sound
        return memoryview(wrapper)

    def _on_wrapper_dead(self, gen: int) -> None:
        with self._lock:
            self._freed = max(self._freed, gen)
            recycle = self._maybe_recycle_locked()
            discard = self._discard
            ring = self.standing and not self._retired
        if recycle:
            self.pool._recycle(self.pr, self.cls, discard=discard)
        elif ring:
            self.pr.doorbell_store(gen)

    def release(self, discard: bool = False) -> None:
        """Return the region without (further) delivery: refused/aborted
        transfer, or link teardown with the region claimed/standing. If a
        delivered wrapper is still aliased, the actual recycle defers to
        its finalize.

        ``discard=True`` (the PEER-DEATH path): the region is destroyed
        instead of pooled — a straggling sender on the dead connection may
        still hold a window and land a late one-sided write, which must hit
        orphaned memory, never a region re-leased to a new transfer (the
        same stale-write rule Pair.init enforces by never reusing ring
        regions across connections)."""
        with self._lock:
            self._retired = True
            if discard:
                self._discard = True
            recycle = self._maybe_recycle_locked()
            discard = self._discard
        if recycle:
            self.pool._recycle(self.pr, self.cls, discard=discard)


class LandingPool:
    """Per-domain pool of registered, 64B-aligned landing regions.

    Regions are allocated from the :class:`~tpurpc.core.pair.MemoryDomain`
    named by ``kind`` (shm for cross-process on one host, the pair's own
    domain on ring planes, verbs on RDMA hardware), pooled by power-of-two
    size class under a byte budget, and recycled only when provably
    unobservable (see :meth:`RegionLease.deliver`)."""

    #: lint rule `lock`: the free lists, zombie quarantine and byte budget
    #: are shared between reader threads, finalizers and lease callers
    _GUARDED_BY = {"_free": "_lock", "_zombies": "_lock",
                   "_allocated": "_lock"}

    def __init__(self, kind: str, budget: Optional[int] = None):
        self.kind = kind
        self._domain = _pair.make_domain(kind)
        self._lock = make_lock("LandingPool._lock")
        self._free: Dict[int, List[_PoolRegion]] = {}
        #: discarded (death-quarantined) regions still pinned by consumer
        #: aliases; close retried on later pool activity, never re-leased
        self._zombies: List[_PoolRegion] = []
        self._allocated = 0
        self._budget = budget if budget is not None else _pool_budget()

    @staticmethod
    def _try_close(pr: _PoolRegion) -> bool:
        """Non-blocking best-effort region destruction (the GC-callback
        discard path must never sit in Region.close's bounded retry)."""
        try:
            pr.region.buf.release()
        except BufferError:
            return False
        try:
            pr.region._close()
        except Exception:
            pass  # the mapping is gone either way at process exit
        return True

    def lease(self, nbytes: int, lease_id: int) -> Optional[RegionLease]:
        """A region of capacity ≥ ``nbytes``, or None when the budget is
        exhausted (the claim is then refused and the sender falls back to
        the framed path — degradation, never a deadlock)."""
        cls = size_class(nbytes)
        with self._lock:
            zombies, self._zombies = self._zombies, []
        if zombies:  # retry quarantined closes off the hot path
            still = [pr for pr in zombies if not self._try_close(pr)]
            if still:
                with self._lock:
                    self._zombies.extend(still)
        with self._lock:
            bucket = self._free.get(cls)
            if bucket:
                pr = bucket.pop()
                pr.doorbell_store(0)  # fresh lease: no consumer history
                return RegionLease(self, pr, lease_id, cls)
            alloc_bytes = cls + _ALIGN + _NONCE_BYTES + _DOORBELL.size
            if self._allocated + alloc_bytes > self._budget:
                return None
            self._allocated += alloc_bytes
        try:
            region = self._domain.alloc(alloc_bytes)
        except Exception:
            with self._lock:
                self._allocated -= alloc_bytes
            return None
        base = np.frombuffer(region.buf, np.uint8)
        offset = int((-base.ctypes.data) % _ALIGN)
        del base
        nonce = os.urandom(_NONCE_BYTES)
        region.buf[offset + cls:offset + cls + _NONCE_BYTES] = nonce
        return RegionLease(self, _PoolRegion(region, offset, cls, nonce),
                           lease_id, cls)

    def _recycle(self, pr: _PoolRegion, cls: int,
                 discard: bool = False) -> None:
        if discard:
            # death-path quarantine: never re-lease a region a straggling
            # peer window might still write; destroy it (deferred to the
            # zombie sweep while consumer aliases pin the mapping)
            with self._lock:
                self._allocated -= (pr.capacity + _ALIGN + _NONCE_BYTES
                                    + _DOORBELL.size)
            if not self._try_close(pr):
                with self._lock:
                    self._zombies.append(pr)
            return
        with self._lock:
            self._free.setdefault(cls, []).append(pr)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "allocated_bytes": self._allocated,
                "free_regions": sum(len(v) for v in self._free.values()),
            }

    def trim(self) -> None:
        """Release every pooled free region back to the OS (atexit / test
        isolation). In-flight and alias-pinned regions are untouched."""
        with self._lock:
            buckets, self._free = self._free, {}
            for bucket in buckets.values():
                for pr in bucket:
                    self._allocated -= (pr.capacity + _ALIGN + _NONCE_BYTES
                                        + _DOORBELL.size)
        for bucket in buckets.values():
            for pr in bucket:
                try:
                    pr.region.close()
                except Exception:
                    pass  # an alias raced the trim; the region stays mapped


_pools: Dict[str, LandingPool] = {}
_pools_lock = make_lock("rendezvous._pools_lock")


def landing_pool(kind: str) -> LandingPool:
    """The process-wide landing pool for one domain kind (regions are
    shared across connections; per-link lease registries keep the death-
    release story per connection)."""
    pool = _pools.get(kind)
    if pool is None:
        with _pools_lock:
            pool = _pools.get(kind)
            if pool is None:
                pool = _pools[kind] = LandingPool(kind)
    return pool


def _trim_pools_atexit() -> None:
    for pool in list(_pools.values()):
        pool.trim()
    # Regions still pinned by live consumer aliases (an app holding a
    # decoded tensor at exit) cannot close; at interpreter teardown their
    # SharedMemory destructors would each print an unraisable BufferError
    # ("Exception ignored in __del__") for a condition that is expected and
    # harmless — the OS reclaims the mappings with the process. Neutralize
    # the destructor AFTER the orderly trim; explicit close paths all ran
    # (or can no longer run) by now.
    try:
        from multiprocessing import shared_memory

        shared_memory.SharedMemory.__del__ = lambda self: None
    except Exception:
        pass


import atexit  # noqa: E402  (registration belongs next to what it cleans)

atexit.register(_trim_pools_atexit)


# ---------------------------------------------------------------------------
# Wire payload codecs (control messages are tiny; clarity over cleverness).
# ---------------------------------------------------------------------------

def _pack_offer(req_id: int, nbytes: int, kinds: Sequence[str]) -> bytes:
    return _OFFER.pack(req_id, nbytes) + ",".join(kinds).encode()


def _unpack_offer(payload) -> Tuple[int, int, List[str]]:
    buf = bytes(payload)
    req_id, nbytes = _OFFER.unpack_from(buf)
    kinds = buf[_OFFER.size:].decode() or ""
    return req_id, nbytes, [k for k in kinds.split(",") if k]


def _pack_claim(req_id: int, lease: Optional[RegionLease]) -> bytes:
    if lease is None:
        return _CLAIM_HDR.pack(req_id, 0, 0)
    kind, handle, offset, capacity, nonce, standing = lease.claim_fields()
    kb = kind.encode()
    return (_CLAIM_HDR.pack(req_id, lease.lease_id, 1)
            + _CLAIM_REG.pack(offset, capacity, nonce, 1 if standing else 0)
            + bytes([len(kb)]) + kb + handle.encode())


class _Claim:
    """Sender-side view of a claimed region. A STANDING claim is reusable:
    after each COMPLETE the sender bumps ``used`` and may write again only
    once the region's doorbell word (consumer-freed count, stored by the
    receiver's wrapper finalize) catches up — zero control frames per
    steady-state transfer."""

    __slots__ = ("lease_id", "kind", "handle", "offset", "capacity",
                 "nonce", "standing", "used", "inflight")

    def __init__(self, lease_id, kind, handle, offset, capacity, nonce,
                 standing=False):
        self.lease_id = lease_id
        self.kind = kind
        self.handle = handle
        self.offset = offset
        self.capacity = capacity
        self.nonce = nonce
        self.standing = standing
        self.used = 0
        self.inflight = False  # a sender thread owns this claim right now


def _unpack_claim(payload) -> Tuple[int, Optional[_Claim]]:
    buf = bytes(payload)
    req_id, lease_id, ok = _CLAIM_HDR.unpack_from(buf)
    if not ok:
        return req_id, None
    pos = _CLAIM_HDR.size
    offset, capacity, nonce, standing = _CLAIM_REG.unpack_from(buf, pos)
    pos += _CLAIM_REG.size
    klen = buf[pos]
    pos += 1
    kind = buf[pos:pos + klen].decode()
    handle = buf[pos + klen:].decode()
    return req_id, _Claim(lease_id, kind, handle, offset, capacity, nonce,
                          standing=bool(standing))


# ---------------------------------------------------------------------------
# The link: one per framed connection, both roles.
# ---------------------------------------------------------------------------

class _CtrlFrameCoalescer:
    """Self-clocking writev combiner for FRAMED control ops — PR 3's
    FrameWriter discipline applied to the rendezvous control plane's cold
    path: the first sender flushes directly; ops arriving while a flush is
    in flight queue and drain in ONE multi-frame send (``send_ops``), so a
    burst of COMPLETEs from N streams costs one transport write instead of
    N.  An idle link pays zero added latency (no timer).  Transports
    without a multi-op send (``send_ops=None`` — the h2 planes) send
    per-op; FIFO order is preserved either way."""

    _GUARDED_BY = {"_pending": "_mu", "_flushing": "_mu"}

    def __init__(self, send_op: Callable[[int, int, bytes], None],
                 send_ops: Optional[Callable] = None):
        self._send_op = send_op
        self._send_ops = send_ops
        self._mu = make_lock("_CtrlFrameCoalescer._mu")
        self._pending: List[Tuple[int, int, bytes]] = []
        self._flushing = False

    def send(self, op: int, stream_id: int, payload: bytes) -> None:
        if self._send_ops is None:
            self._send_op(op, stream_id, payload)
            return
        with self._mu:
            self._pending.append((op, stream_id, payload))
            if self._flushing:
                return  # the in-flight flusher writes it
            self._flushing = True
        while True:
            with self._mu:
                batch, self._pending = self._pending, []
                if not batch:
                    self._flushing = False
                    return
            try:
                if len(batch) == 1:
                    self._send_op(*batch[0])
                else:
                    self._send_ops(batch)
                    _stats.batch_hist("ctrl_coalesce").record(len(batch))
            except BaseException:
                # connection dying: drop the queue (every control path
                # treats sends as best-effort; link close releases leases)
                with self._mu:
                    self._pending = []
                    self._flushing = False
                raise


#: cross-link window reuse — every hit is a writer QP + bounce
#: registration NOT created (verbs) or a mmap/attach NOT repeated (shm)
_WINDOW_SHARE_HITS = _metrics.counter("rdv_window_share_hits")


class _WindowShare:
    """Process-wide refcounted cache of open peer-region windows keyed
    ``(kind, handle)`` — the rendezvous half of the ISSUE 16 shared-MR
    plane. Ten links (or ten thousand pairs' links) writing into the same
    peer arena share ONE open window — on verbs that is one writer QP and
    one bounce registration instead of one per link, which is how the
    registration count stays O(distinct regions × size-classes) rather
    than O(pairs).

    ``acquire`` bumps a refcount (opening on a miss); ``release`` drops
    it, parking a zero-ref window on a bounded idle LRU so the next
    acquirer of the same region skips the open entirely. Windows are
    opened on the share's OWN domains, never a link's, so a shared window
    cannot die with whichever link happened to open it first.

    Write safety across holders: a claim/grant leases a region to exactly
    one transfer at a time, and the verbs bounce staging is offset-mapped
    (window offset == bounce offset), so concurrent holders writing
    disjoint claimed spans never collide — the argument that makes
    per-link window reuse sound extends unchanged across links.
    """

    _GUARDED_BY = {"_entries": "_lock", "_idle": "_lock",
                   "_domains": "_lock"}

    _MAX_IDLE = 64

    def __init__(self):
        self._lock = make_lock("WindowShare._lock")
        #: key -> [window, refcount, window_bytes]
        self._entries: Dict[Tuple[str, str], list] = {}
        self._idle: List[Tuple[str, str]] = []  # refcount-0 keys, LRU
        self._domains: Dict[str, _pair.MemoryDomain] = {}

    def _domain(self, kind: str) -> _pair.MemoryDomain:
        with self._lock:
            d = self._domains.get(kind)
            if d is None:
                d = self._domains[kind] = _pair.make_domain(kind)
            return d

    def acquire(self, kind: str, handle: str, nbytes: int) -> _pair.Window:
        key = (kind, handle)
        stale = None
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if e[2] >= nbytes:
                    if e[1] == 0:
                        try:
                            self._idle.remove(key)
                        except ValueError:
                            pass
                    e[1] += 1
                    _WINDOW_SHARE_HITS.inc()
                    return e[0]
                if e[1] == 0:
                    # undersized and idle: retire it, reopen bigger below
                    stale = self._entries.pop(key)
                    try:
                        self._idle.remove(key)
                    except ValueError:
                        pass
        if stale is not None:
            try:
                stale[0].close()
            except Exception:
                pass
        win = self._domain(kind).open_window(handle, nbytes)
        with self._lock:
            if key not in self._entries:
                self._entries[key] = [win, 1, nbytes]
                return win
        # raced another opener, or an undersized entry is still
        # referenced: hand out a PRIVATE window — release()'s identity
        # check routes it straight to close instead of the refcount
        return win

    def release(self, kind: str, handle: str, win: _pair.Window) -> None:
        key = (kind, handle)
        close_now = []
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e[0] is win:
                if e[1] > 0:
                    e[1] -= 1
                    if e[1] == 0:
                        self._idle.append(key)
                        while len(self._idle) > self._MAX_IDLE:
                            k = self._idle.pop(0)
                            dead = self._entries.pop(k, None)
                            if dead is not None:
                                close_now.append(dead[0])
            else:
                close_now.append(win)  # private window (see acquire)
        for w in close_now:
            try:
                w.close()
            except Exception:
                pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries),
                    "idle": len(self._idle),
                    "referenced": sum(1 for e in self._entries.values()
                                      if e[1] > 0)}

    def drain(self) -> None:
        """Close every cached window and domain (test isolation; callers
        must have released their refs — a drained-under window fails its
        next write, same as a closed link's would)."""
        with self._lock:
            wins = [e[0] for e in self._entries.values()]
            self._entries.clear()
            self._idle = []
            domains = list(self._domains.values())
            self._domains.clear()
        for w in wins:
            try:
                w.close()
            except Exception:
                pass
        for d in domains:
            try:
                d.close()
            except Exception:
                pass


_WINDOW_SHARE: Optional[_WindowShare] = None
_WINDOW_SHARE_LOCK = make_lock("rendezvous._WINDOW_SHARE")


def window_share() -> _WindowShare:
    global _WINDOW_SHARE
    with _WINDOW_SHARE_LOCK:
        if _WINDOW_SHARE is None:
            _WINDOW_SHARE = _WindowShare()
        return _WINDOW_SHARE


class RdvLink:
    """Rendezvous state for ONE framed connection: the sender role (offer,
    one-sided write, complete) and the receiver role (pool leases, claims,
    zero-copy delivery) — every connection carries both directions.

    Transport-agnostic: the owning connection supplies ``send_op(op,
    stream_id, payload)`` (frame the control message), ``deliver(stream_id,
    flags, wrapper)`` (hand a completed payload to the stream layer), and
    optionally ``pump(pred, deadline)`` for inline-pump transports where
    the waiting sender must drive the reader itself."""

    #: lint rule `lock`: every registry below is shared between the
    #: connection reader/pump thread, sender threads and the death path
    _GUARDED_BY = {"_reqs": "_lock", "_grants": "_lock",
                   "_leases": "_lock", "_req_lease": "_lock",
                   "_pregrants_out": "_lock", "_windows": "_lock",
                   "_window_order": "_lock"}

    def __init__(self, name: str,
                 send_op: Callable[[int, int, bytes], None],
                 deliver: Callable[[int, int, object], None],
                 pool_kinds: Sequence[str] = ("shm",),
                 open_kinds: Sequence[str] = ("shm", "local"),
                 pump: Optional[Callable] = None,
                 send_ops: Optional[Callable] = None):
        self._send_op = send_op
        self._coalescer = _CtrlFrameCoalescer(send_op, send_ops)
        #: tpurpc-pulse seams, bound by the owning connection when its
        #: descriptor-ring plane arms: ``ctrl_post(op, sid, payload) ->
        #: bool`` places a control op in the peer's ring (True = the
        #: framed path must NOT also send it); ``ctrl_drain()`` consumes
        #: this side's ring from a sender thread (pregrant pickup)
        self.ctrl_post: Optional[Callable[[int, int, bytes], bool]] = None
        self.ctrl_drain: Optional[Callable[[], int]] = None
        self._deliver = deliver
        self._pool_kinds = tuple(pool_kinds)
        self._open_kinds = tuple(open_kinds)
        self._pump = pump
        self._lock = make_lock("RdvLink._lock")
        self._cond = make_condition("RdvLink._cond", self._lock)
        self.negotiated = False
        self.closed = False
        #: reader-thread ident the sender must never block on (a claim wait
        #: there would deadlock against the claim's own delivery)
        self.disallowed_thread: Optional[int] = None
        #: the connection's max_receive_message_length (None/negative =
        #: unlimited): offers past it are REFUSED, pushing the transfer to
        #: the framed path whose oversize machinery rejects it with the
        #: proper RESOURCE_EXHAUSTED — the bulk plane must not become a
        #: receive-limit bypass
        self.recv_limit: Optional[int] = None
        self._req_ids = itertools.count(1)
        self._lease_ids = itertools.count(1)
        self._reqs: Dict[int, dict] = {}            # sender: req -> state
        self._grants: Dict[int, List[_Claim]] = {}  # sender: cls -> claims
        self._leases: Dict[int, RegionLease] = {}   # receiver: id -> lease
        self._req_lease: Dict[int, int] = {}        # receiver: req -> lease
        self._pregrants_out: Dict[int, int] = {}    # receiver: cls -> count
        self._windows: Dict[Tuple[str, str], _pair.Window] = {}
        self._window_order: List[Tuple[str, str]] = []
        self._domains: Dict[str, _pair.MemoryDomain] = {}
        self._ftag = _flight.tag_for("rdv:" + name)

    # -- negotiation ---------------------------------------------------------

    def on_peer_hello(self, payload: bytes = b"") -> None:
        """The peer demonstrated it speaks the rendezvous control frames
        (hello PING on the native framing, the custom SETTINGS id on h2)."""
        self.negotiated = True

    # -- control send seam (tpurpc-pulse) -------------------------------------

    def _ctrl_send(self, op: int, stream_id: int, payload: bytes,
                   ring_ok: bool = True) -> None:
        """Send one control op: descriptor ring when the link adopted one
        (zero frames, zero wakeups), else the framed path through the
        self-clocking coalescer.  Ring failures (full, closed, oversized)
        degrade to framed — never a lost op, never an exception for the
        degradation itself; framed-path transport errors propagate exactly
        as ``send_op``'s always did.

        ``ring_ok=False`` pins the op to the framed path: a COMPLETE whose
        payload rode an ASYNCHRONOUS landing domain (tcp_window records,
        verbs WRs — anything without a host-addressable view) is ordered
        after the payload only by the shared record/QP stream the framed
        connection rides; a ring-posted COMPLETE would overtake the bytes
        and deliver a torn region (caught live by the tcpw cross-process
        test)."""
        post = self.ctrl_post
        if post is not None and ring_ok:
            try:
                if _transport.dispatch("post", self, post, op, stream_id,
                                       payload):
                    return
            except Exception:
                pass  # ring tearing down: the framed path still works
        t0 = time.monotonic_ns()
        _transport.dispatch("frame", self, self._coalescer.send, op,
                            stream_id, payload)
        _RDV_CTRL_FRAMES.inc()
        n = len(payload)
        dt = time.monotonic_ns() - t0
        _LENS_CTRL_BYTES.inc(n)
        _LENS_CTRL_NS.inc(dt)

    # -- sender role ---------------------------------------------------------

    def eligible(self, total: int, flags_compressed: bool = False) -> bool:
        return (self.negotiated and not self.closed and enabled()
                and not flags_compressed
                and total >= min_bytes() and total <= _MAX_TRANSFER
                and threading.get_ident() != self.disallowed_thread)

    def send_message(self, stream_id: int, flags: int,
                     segs: Sequence, total: int) -> bool:
        """Move one whole MESSAGE payload via rendezvous. True when the
        payload was placed and COMPLETE sent (the framed path must NOT also
        send it); False to fall back to the framed path — refused claim,
        timeout, write failure — never an exception for fallback cases."""
        cls = size_class(total)
        claim = self._take_grant(cls, total)
        if claim is None and self._has_standing(cls, total):
            # tpurpc-pulse: every standing region's doorbell is behind —
            # the consumer is mid-batch.  A solicited claim here costs a
            # full control round trip (~0.8 ms on this rig); a bounded
            # yield-poll of the doorbells (draining our ctrl ring for
            # pregrant top-ups as we go) hands the core to the consumer
            # and almost always turns up a freed region in a few slices.
            deadline = time.monotonic() + 0.002
            drain = self.ctrl_drain
            while claim is None and time.monotonic() < deadline:
                if drain is not None:
                    try:
                        drain()
                    except Exception:
                        drain = None
                time.sleep(0)
                claim = self._take_grant(cls, total)
        if claim is None:
            claim = self.rdv_claim(stream_id, total, cls)
        if claim is None:
            _RDV_FALLBACK.inc()
            return False
        wedge = TEST_HOOKS.get("wedge_after_claim")
        if wedge is not None:
            while not wedge.wait(timeout=0.05):  # pragma: no cover - chaos
                if self.closed:
                    break
        try:
            self._rdv_write(claim, segs, total)
        except BaseException:
            self._drop_grant(claim)
            self.rdv_release(claim)
            _RDV_FALLBACK.inc()
            return False
        self.rdv_complete(claim, stream_id, flags, total)
        _RDV_SENT.inc()
        return True

    def _take_grant(self, cls: int, total: int) -> Optional[_Claim]:
        """A usable cached grant: a one-shot claim is consumed; a STANDING
        claim is acquired (inflight flag) and reused only when its doorbell
        shows every previous delivery's aliases died — the zero-frame
        steady-state path."""
        with self._lock:
            if self.closed:
                return None
            bucket = list(self._grants.get(cls) or ())
        for claim in bucket:
            if claim.capacity < total:
                continue
            if not claim.standing:
                with self._lock:
                    b = self._grants.get(cls)
                    if b is not None and claim in b:
                        b.remove(claim)
                        return claim
                continue
            with self._lock:
                if claim.inflight:
                    continue
                claim.inflight = True
            if self._standing_free(claim):
                return claim
            with self._lock:
                claim.inflight = False
        return None

    def _has_standing(self, cls: int, total: int) -> bool:
        """Any STANDING cached grant big enough (busy or not) — the signal
        that a freed doorbell, not a new claim, is what's worth waiting
        a moment for."""
        with self._lock:
            bucket = self._grants.get(cls) or ()
            return any(c.standing and c.capacity >= total for c in bucket)

    def _standing_free(self, claim: _Claim) -> bool:
        """Has the receiver's consumer freed every previous use? Reads the
        region-resident doorbell word through the sender's mapped window —
        no control frame. Non-view domains can't read it and answer False
        (they stay on explicit offer/claim rounds)."""
        try:
            win = self._window_for(claim)
        except Exception:
            return False
        view = win.view
        if view is None:
            return False
        db = claim.offset + claim.capacity + _NONCE_BYTES
        try:
            (freed,) = _DOORBELL.unpack_from(view, db)
        except (ValueError, struct.error):
            return False
        return freed == claim.used

    def _drop_grant(self, claim: _Claim) -> None:
        """Forget a cached grant after a failed write (its region is being
        released): it must not be reused."""
        with self._lock:
            claim.inflight = False
            b = self._grants.get(size_class(claim.capacity))
            if b is not None and claim in b:
                b.remove(claim)

    def rdv_claim(self, stream_id: int, total: int,
                  cls: int) -> Optional[_Claim]:
        """OFFER the transfer and wait (pumping where the transport needs
        it) for the peer's CLAIM. None = refused or timed out (the offer is
        then explicitly abandoned with a RELEASE so a crossing claim frees
        its region)."""
        req = next(self._req_ids)
        st = {"claim": _SENTINEL_PENDING}
        with self._lock:
            if self.closed:
                return None
            self._reqs[req] = st
        _flight.emit(_flight.RDV_OFFER, self._ftag, req, total)
        try:
            self._ctrl_send(OP_OFFER, stream_id,
                          _pack_offer(req, total, self._open_kinds))
        except Exception:
            with self._lock:
                self._reqs.pop(req, None)
            return None
        deadline = time.monotonic() + _claim_timeout()

        def pred() -> bool:
            return st["claim"] is not _SENTINEL_PENDING or self.closed

        if self._pump is not None:
            self._pump(pred, deadline)
        else:
            with self._cond:
                while not pred():
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        break
                    self._cond.wait(remain)
        with self._lock:
            self._reqs.pop(req, None)
            claim = st["claim"]
        if claim is _SENTINEL_PENDING:
            # timed out: abandon the offer — a claim crossing this release
            # on the wire finds no pending request and is released by
            # on_claim's unknown-request path
            _flight.emit(_flight.RDV_RELEASE, self._ftag, 0, req)
            try:
                self._ctrl_send(OP_RELEASE, 0, _RELEASE.pack(0, req))
            except Exception:
                pass
            return None
        if claim is _SENTINEL_REFUSED or claim is None:
            return None
        _flight.emit(_flight.RDV_CLAIM, self._ftag, req, claim.lease_id)
        return claim

    def _window_for(self, claim: _Claim) -> _pair.Window:
        key = (claim.kind, claim.handle)
        win = self._windows.get(key)
        if win is not None:
            return win
        # the per-link map holds a REF on the process-wide share — the
        # open (QP connect + bounce registration on verbs) happens at most
        # once per region across every link in the process
        win = window_share().acquire(
            claim.kind, claim.handle,
            claim.offset + claim.capacity + _NONCE_BYTES + _DOORBELL.size)
        extra = None
        evict_key = None
        evict_win = None
        with self._lock:
            prev = self._windows.get(key)
            if prev is not None:
                extra, win = win, prev  # raced a sibling sender thread
            else:
                self._windows[key] = win
                self._window_order.append(key)
                if len(self._window_order) > _WINDOW_CACHE:
                    evict_key = self._window_order.pop(0)
                    evict_win = self._windows.pop(evict_key, None)
        if extra is not None:
            window_share().release(claim.kind, claim.handle, extra)
        if evict_win is not None:
            window_share().release(evict_key[0], evict_key[1], evict_win)
        return win

    def _rdv_write(self, claim: _Claim, segs: Sequence, total: int) -> None:
        """The one-sided placement: every gather segment lands directly in
        the claimed region — no staging join, no landing copy on the other
        side. One RDMA WRITE per segment on the verbs domain, one
        memoryview copy per segment on the software domains."""
        t0 = time.monotonic_ns()
        win = self._window_for(claim)
        view = win.view
        if view is not None:
            if claim.nonce and bytes(
                    view[claim.offset + claim.capacity:
                         claim.offset + claim.capacity + _NONCE_BYTES]
                    ) != claim.nonce:
                raise OSError("rendezvous region nonce mismatch: the "
                              "claimed handle resolves to different memory "
                              "on this host")

        def _place() -> None:
            off = claim.offset
            if view is not None:
                for seg in segs:
                    sv = memoryview(seg).cast("B")
                    view[off:off + len(sv)] = sv
                    off += len(sv)
            else:
                for seg in segs:
                    sv = memoryview(seg).cast("B")
                    win.write(off, sv)
                    off += len(sv)

        # the one-sided landing is a cross-process message: under simnet
        # the store itself becomes a deliverable, reorderable event (a
        # straggler's write must land only in quarantined memory)
        _transport.dispatch("write", self, _place)
        _ledger.rdma_write(total)
        dt = time.monotonic_ns() - t0
        _LENS_RDV_NS.inc(dt)
        _LENS_RDV_BYTES.inc(total)
        _LENS_RDV_COPY.inc(total)

    def rdv_complete(self, claim: _Claim, stream_id: int, flags: int,
                     total: int) -> None:
        if not claim.standing:
            # solicited transfers are edges worth recording; standing-
            # region reuse is steady-state traffic and stays silent (the
            # flight recorder's edges-not-traffic contract)
            _flight.emit(_flight.RDV_WRITE, self._ftag, claim.lease_id,
                         total)
            _flight.emit(_flight.RDV_COMPLETE, self._ftag, claim.lease_id,
                         total)
        with self._lock:
            claim.used += 1
            claim.inflight = False
            # a view-backed (synchronous shm/local) landing write is
            # visible the moment it returns, so its COMPLETE may ride the
            # ring; an async domain's bytes are still in flight on the
            # record/QP stream — only the framed path (same stream)
            # sequences the COMPLETE after them
            win = self._windows.get((claim.kind, claim.handle))
        sync_write = win is not None and win.view is not None
        self._ctrl_send(OP_COMPLETE, stream_id,
                        _COMPLETE.pack(claim.lease_id, total, flags & 0xFF),
                        ring_ok=sync_write)

    def rdv_release(self, claim: _Claim) -> None:
        """Abandon a claimed region without completing (write failure,
        cancelled transfer): the peer frees it for reuse."""
        _flight.emit(_flight.RDV_RELEASE, self._ftag, claim.lease_id, 0)
        try:
            self._ctrl_send(OP_RELEASE, 0, _RELEASE.pack(claim.lease_id, 0))
        except Exception:
            pass

    # -- receiver role -------------------------------------------------------

    def on_op(self, op: int, stream_id: int, payload) -> None:
        """Dispatch one control frame (called from the connection's reader/
        pump). Never raises — a malformed control message degrades to a
        refused/ignored transfer, not a dead connection."""
        try:
            if op == OP_OFFER:
                self.on_offer(stream_id, payload)
            elif op == OP_CLAIM:
                self.on_claim(payload)
            elif op == OP_COMPLETE:
                self.on_complete(stream_id, payload)
            elif op == OP_RELEASE:
                self.on_release(payload)
        except Exception:
            from tpurpc.utils.trace import trace_endpoint

            trace_endpoint.log("rendezvous control op %d failed", op)

    def on_offer(self, stream_id: int, payload) -> None:
        req, nbytes, kinds = _unpack_offer(payload)
        _flight.emit(_flight.RDV_OFFER, self._ftag, req, nbytes)
        if TEST_HOOKS.get("drop_offers"):
            return  # chaos seam: starve the sender's claim wait
        lease = self._lease_for(nbytes, kinds)
        if lease is None:
            _RDV_REFUSED.inc()
            self._ctrl_send(OP_CLAIM, stream_id, _pack_claim(req, None))
            return
        with self._lock:
            if self.closed:
                lease.release()
                return
            self._leases[lease.lease_id] = lease
            self._req_lease[req] = lease.lease_id
        _flight.emit(_flight.RDV_CLAIM, self._ftag, req, lease.lease_id)
        self._ctrl_send(OP_CLAIM, stream_id, _pack_claim(req, lease))

    def _lease_for(self, nbytes: int, kinds: Sequence[str]
                   ) -> Optional[RegionLease]:
        if not enabled() or nbytes > _MAX_TRANSFER:
            return None
        limit = self.recv_limit
        if limit is not None and limit >= 0 and nbytes > limit:
            return None  # refusal → framed path → RESOURCE_EXHAUSTED there
        for kind in self._pool_kinds:
            if kind not in kinds:
                continue
            try:
                # ownership transfers by return: the caller registers the
                # lease in _leases and every death path releases it there
                lease = landing_pool(kind).lease(  # tpr: allow(ringpool)
                    nbytes, next(self._lease_ids))
            except Exception:
                continue
            if lease is not None:
                return lease
        return None

    def on_claim(self, payload) -> None:
        req, claim = _unpack_claim(payload)
        if req == 0:
            # unsolicited pre-grant: cache it for the next same-class send
            if claim is not None:
                with self._lock:
                    if self.closed:
                        pass  # receiver's close releases everything anyway
                    else:
                        self._grants.setdefault(claim.capacity,
                                                []).append(claim)
            return
        with self._lock:
            st = self._reqs.get(req)
            if st is not None:
                st["claim"] = claim if claim is not None \
                    else _SENTINEL_REFUSED
                self._cond.notify_all()
                return
        # the sender already gave up on this request (timeout raced the
        # claim): hand the region straight back
        if claim is not None:
            try:
                self._ctrl_send(OP_RELEASE, 0,
                              _RELEASE.pack(claim.lease_id, 0))
            except Exception:
                pass

    def on_complete(self, stream_id: int, payload) -> None:
        lease_id, nbytes, flags = _COMPLETE.unpack(bytes(payload))
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is not None and not lease.standing:
                # one-shot lease: consumed by this completion. STANDING
                # leases stay claimed — the sender reuses the region on
                # the doorbell with no further grants.
                del self._leases[lease_id]
                for r, lid in list(self._req_lease.items()):
                    if lid == lease_id:
                        del self._req_lease[r]
        if lease is None:
            return  # already released (crossed a release) — drop
        if not lease.pregrant:
            _flight.emit(_flight.RDV_COMPLETE, self._ftag, lease_id, nbytes)
        try:
            wrapper = lease.deliver(nbytes)
        except Exception:
            # protocol violation (oversized complete / reuse while the
            # previous delivery is aliased): drop the region entirely —
            # its pool recycle re-zeroes the doorbell, so a confused
            # sender can never land bytes in it again
            with self._lock:
                self._leases.pop(lease_id, None)
                if lease.pregrant:
                    self._pregrants_out[lease.cls] = max(
                        0, self._pregrants_out.get(lease.cls, 1) - 1)
            lease.release(discard=True)  # a confused sender may write again
            return
        _RDV_RECV.inc()
        cls, kind = lease.cls, lease.kind
        self._deliver(stream_id, flags, wrapper)
        self._maybe_pregrant(cls, kind)

    def _maybe_pregrant(self, cls: int, kind: str) -> None:
        """RDMAbox discipline: keep STANDING regions granted for the
        classes the peer is actively streaming, topped up to
        ``_PREGRANT_DEPTH``. A standing grant costs one claim frame EVER:
        after each use the consumer-done signal rides the region's own
        doorbell word, so steady-state transfers carry exactly one control
        frame (the COMPLETE) and zero claim round trips."""
        while True:
            with self._lock:
                if (self.closed or self._pregrants_out.get(
                        cls, 0) >= _PREGRANT_DEPTH):
                    return
            try:
                lease = landing_pool(kind).lease(cls, next(self._lease_ids))
            except Exception:
                return
            if lease is None:
                return
            lease.pregrant = True
            lease.standing = True
            with self._lock:
                if self.closed:
                    lease.release()
                    return
                self._leases[lease.lease_id] = lease
                self._pregrants_out[cls] = self._pregrants_out.get(cls,
                                                                   0) + 1
            try:
                self._ctrl_send(OP_CLAIM, 0, _pack_claim(0, lease))
            except Exception:
                with self._lock:
                    self._leases.pop(lease.lease_id, None)
                    self._pregrants_out[cls] = max(
                        0, self._pregrants_out.get(cls, 1) - 1)
                lease.release()
                return

    def on_release(self, payload) -> None:
        lease_id, req = _RELEASE.unpack(bytes(payload))
        with self._lock:
            if not lease_id and req:
                lease_id = self._req_lease.pop(req, 0)
            lease = self._leases.pop(lease_id, None)
            if lease is not None and lease.pregrant:
                self._pregrants_out[lease.cls] = max(
                    0, self._pregrants_out.get(lease.cls, 1) - 1)
        if lease is not None:
            _flight.emit(_flight.RDV_RELEASE, self._ftag, lease_id, req)
            lease.release()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Connection teardown / peer death: every claimed region is
        released back to its pool (the modeled peer-death invariant), every
        waiting sender is woken to fall back or fail with the transport."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            leases = list(self._leases.values())
            self._leases.clear()
            self._req_lease.clear()
            self._pregrants_out.clear()
            self._grants.clear()
            windows = list(self._windows.items())
            self._windows.clear()
            self._window_order = []
            self._cond.notify_all()
        for lease in leases:
            # teardown is an EDGE (once per connection death), so every
            # claimed region's release is recorded — standing grants
            # included; the postmortem's claim→death→release story needs it
            _flight.emit(_flight.RDV_RELEASE, self._ftag,
                         lease.lease_id, 0)
            # DISCARD, don't pool: the peer (or a straggling sender thread
            # on this dying connection) may still hold a window and land a
            # late one-sided write — it must hit orphaned memory, never a
            # region re-leased to a new transfer
            lease.release(discard=True)
        for (kind, handle), win in windows:
            # drop this link's refs; the share parks or closes as the
            # cross-link refcount dictates
            window_share().release(kind, handle, win)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "negotiated": int(self.negotiated),
                "claimed_leases": len(self._leases),
                "cached_grants": sum(len(v) for v in
                                     self._grants.values()),
            }


# ---------------------------------------------------------------------------
# Block-granular standing grants (tpurpc-keystone, ISSUE 11).
#
# The LandingPool leases CONTIGUOUS size-classed spans; the KV plane's unit
# is the BLOCK — a grant names a scatter of block offsets inside one
# registered arena region (the decode server's KvBlockManager), and the
# sender one-sided-writes each block straight into place: KV lands in the
# decode arena with zero host landing copies and zero staging joins. A
# grant is STANDING in the RDMAbox sense at the window level: the sender's
# GrantWriter keeps one open window per (kind, handle), so a stream of
# handoffs into the same arena pays the window-open exactly once.
# ---------------------------------------------------------------------------

_GRANT_HDR = struct.Struct("<QIIQQ16s")  # grant_id, block_bytes, n_offsets,
#                                          window_bytes, nonce_off, nonce


class BlockGrant:
    """A peer-advertised landing descriptor at block granularity: which
    blocks of which registered region the sender may write, plus the
    anti-mixup nonce (stored at ``nonce_off`` inside the region — the
    writer verifies it through its window before placing a byte, the same
    stale-handle defense as RegionLease's trailer nonce)."""

    __slots__ = ("grant_id", "kind", "handle", "block_bytes", "offsets",
                 "window_bytes", "nonce", "nonce_off")

    def __init__(self, grant_id: int, kind: str, handle: str,
                 block_bytes: int, offsets: Sequence[int],
                 window_bytes: int, nonce: bytes, nonce_off: int):
        self.grant_id = int(grant_id)
        self.kind = kind
        self.handle = handle
        self.block_bytes = int(block_bytes)
        self.offsets = tuple(int(o) for o in offsets)
        self.window_bytes = int(window_bytes)
        self.nonce = bytes(nonce)
        self.nonce_off = int(nonce_off)

    @property
    def capacity(self) -> int:
        return self.block_bytes * len(self.offsets)

    def to_wire(self) -> bytes:
        kb = self.kind.encode()
        return (_GRANT_HDR.pack(self.grant_id, self.block_bytes,
                                len(self.offsets), self.window_bytes,
                                self.nonce_off, self.nonce)
                + bytes([len(kb)]) + kb + self.handle.encode()
                + b"\x00" + b"".join(struct.pack("<Q", o)
                                     for o in self.offsets))

    @classmethod
    def from_wire(cls, payload) -> "BlockGrant":
        buf = bytes(payload)
        (grant_id, block_bytes, n, window_bytes, nonce_off,
         nonce) = _GRANT_HDR.unpack_from(buf)
        pos = _GRANT_HDR.size
        klen = buf[pos]
        pos += 1
        kind = buf[pos:pos + klen].decode()
        pos += klen
        end = buf.index(b"\x00", pos)
        handle = buf[pos:end].decode()
        pos = end + 1
        offsets = struct.unpack_from(f"<{n}Q", buf, pos)
        return cls(grant_id, kind, handle, block_bytes, offsets,
                   window_bytes, nonce, nonce_off)


class GrantWriter:
    """The sender half of block-granular grants: opens (and CACHES — the
    standing discipline) one window per (kind, handle), verifies the
    grant's nonce, then places each chunk with a one-sided write. All
    placement bytes ride the ``rendezvous`` lens hop and the ledger's
    ``rdma_write`` — the same accounting as RdvLink's bulk path, so the
    copy-ledger proof ("KV landed with zero host landing copies") is one
    ``ledger.track()`` window away."""

    _GUARDED_BY = {"_windows": "_lock"}

    def __init__(self):
        self._domains: Dict[str, _pair.MemoryDomain] = {}
        self._windows: Dict[Tuple[str, str], _pair.Window] = {}
        self._lock = make_lock("GrantWriter._lock")

    def _window(self, grant: BlockGrant) -> _pair.Window:
        key = (grant.kind, grant.handle)
        win = self._windows.get(key)
        if win is not None:
            return win
        win = window_share().acquire(grant.kind, grant.handle,
                                     grant.window_bytes)
        extra = None
        with self._lock:
            prev = self._windows.get(key)
            if prev is not None:
                extra, win = win, prev
            else:
                self._windows[key] = win
        if extra is not None:
            window_share().release(grant.kind, grant.handle, extra)
        return win

    def write_blocks(self, grant: BlockGrant, chunks: Sequence) -> int:
        """Place ``chunks[i]`` (bytes-like, ≤ block_bytes) at
        ``grant.offsets[i]``. Returns bytes written. Raises on nonce
        mismatch or oversized chunks — the caller releases/abandons the
        grant (the `rdv` pairing discipline applies to grants too)."""
        if len(chunks) > len(grant.offsets):
            raise ValueError(f"{len(chunks)} chunks for a "
                             f"{len(grant.offsets)}-block grant")
        win = self._window(grant)
        view = win.view
        if grant.nonce:
            if view is not None:
                seen = bytes(view[grant.nonce_off:
                                  grant.nonce_off + len(grant.nonce)])
                if seen != grant.nonce:
                    raise OSError(
                        "block-grant nonce mismatch: the granted handle "
                        "resolves to different memory on this host")
        t0 = time.monotonic_ns()
        total = 0
        placed = []
        for off, chunk in zip(grant.offsets, chunks):
            sv = memoryview(chunk).cast("B")
            if len(sv) > grant.block_bytes:
                raise ValueError(f"chunk of {len(sv)} exceeds the "
                                 f"{grant.block_bytes}-byte block")
            placed.append((off, sv))
            total += len(sv)

        def _place() -> None:
            for off, sv in placed:
                if view is not None:
                    view[off:off + len(sv)] = sv
                else:
                    win.write(off, sv)

        # the block placement is a cross-process one-sided write: simnet
        # reorders/crashes it against the COMPLETE that must follow it
        _transport.dispatch("write", self, _place)
        _ledger.rdma_write(total)
        dt = time.monotonic_ns() - t0
        _LENS_RDV_NS.inc(dt)
        _LENS_RDV_BYTES.inc(total)
        _LENS_RDV_COPY.inc(total)
        return total

    def close(self) -> None:
        with self._lock:
            windows = list(self._windows.items())
            self._windows.clear()
        for (kind, handle), win in windows:
            window_share().release(kind, handle, win)


def domains_for_endpoint(endpoint) -> Tuple[Tuple[str, ...],
                                            Tuple[str, ...]]:
    """(pool_kinds, open_kinds) for a connection over ``endpoint``.

    Ring endpoints prefer the pair's own domain (the registered memory the
    connection already trusts — verbs MRs on hardware, shm segments on one
    host, tcp_window regions cross-host, whose shared ordered record
    connection also sequences the COMPLETE after the payload); everything
    else (plain TCP, h2) uses the shm pool, the one-host emulation of a
    registered region. ``open_kinds`` is what OUR sender can open windows
    into — a claim naming anything else is impossible to honor and the
    receiver never issues one (it picks from the offer's kinds)."""
    pair = getattr(endpoint, "pair", None)
    pool: List[str] = []
    if pair is not None:
        kind = pair.domain.kind
        if kind in ("shm", "local", "tcp_window", "verbs"):
            pool.append(kind)
    if "shm" not in pool:
        pool.append("shm")
    open_kinds = list(dict.fromkeys(pool + ["shm", "local"]))
    return tuple(pool), tuple(open_kinds)


def link_for_endpoint(endpoint, name: str,
                      send_op: Callable[[int, int, bytes], None],
                      deliver: Callable[[int, int, object], None],
                      pump: Optional[Callable] = None,
                      send_ops: Optional[Callable] = None
                      ) -> Optional[RdvLink]:
    """An armed-but-unnegotiated link for a new framed connection, or None
    when rendezvous is disabled process-wide.  ``send_ops(list_of_(op,
    sid, payload))`` is the multi-frame control send the cold-path
    coalescer flushes bursts through (native framing only)."""
    if not enabled():
        return None
    pool_kinds, open_kinds = domains_for_endpoint(endpoint)
    return RdvLink(name, send_op, deliver, pool_kinds=pool_kinds,
                   open_kinds=open_kinds, pump=pump, send_ops=send_ops)
