"""``tcp_window`` — the cross-host one-sided memory domain over sockets.

The reference's product is a fast pipe *between hosts*: the sender RDMA-WRITEs
payload straight into the peer's receive ring and credits flow back the same
way (``/root/reference/src/core/lib/ibverbs/pair.cc:587-622`` postWrite,
``:624-641`` updateStatus). Without IB hardware, this module supplies the
second *real* implementation of the :class:`tpurpc.core.pair.MemoryDomain`
seam: a socket-carried one-sided write domain. The pair/ring/credit protocol
above it is byte-for-byte the one the shm domain runs — which is the point:
the seam is proven by two genuinely different fabrics.

Design (and how it mirrors verbs semantics):

- Each process runs ONE record server (lazy singleton). ``alloc`` registers
  a plain local buffer under a 16-byte key and hands out a handle
  ``tcpw:<host>:<port>:<key>:<secret>`` — the moral equivalent of an
  ``ibv_mr`` rkey + raddr envelope (``memory_region.h:14-47``), plus the
  per-region HMAC secret only the bootstrap channel ever carries.
- ``open_window(handle)`` attaches to the peer process's record server.
  ``Window.write(offset, data)`` ships a ``(key, offset, len, payload)``
  record; the peer's applier thread lands it in the region buffer. The
  writer never rendezvouses with the *consumer* — the consuming thread just
  polls its ring memory, exactly as with shm or a NIC's DMA.
- ALL windows from this process to one peer process share a single ordered
  connection (refcounted). That gives the cross-buffer total order an RC QP
  gives the reference: a credit write posted after a data write can never
  be observed before it. (Two sockets would reorder data vs. status and
  break the ring protocol's publication invariant.)
- Writes racing a region's teardown are discarded with a trace log — the
  one-sided analog of writes to a deregistered MR.

The advertised host defaults to ``127.0.0.1`` (CI: cross-process on one
box); set ``TPURPC_TCPW_HOST`` to the host's reachable address for real
cross-host deployments. Select the domain with ``TPURPC_RING_DOMAIN=
tcp_window`` (alias ``GRPC_RDMA_DOMAIN``) on BOTH peers.

Security note: the record stream is a SEPARATE plaintext TCP connection —
TLS on the RPC port encrypts the bootstrap/notify channel but not these
one-sided writes (exactly like the reference, whose RDMA payloads bypass
TLS on the NIC: SURVEY §2.4 "security sits above the endpoint seam").
Write AUTHORIZATION, however, is stronger than possession of the 16-byte
region key: every record carries a truncated HMAC-SHA256 over its header
and payload, keyed by a per-region 32-byte secret that travels only inside
the region handle — i.e. over the bootstrap channel, which CAN be TLS.
A connection that delivers a record failing verification is dropped on the
spot; garbage or forged streams cannot land a single byte in a region
(``tests/test_tcpw.py::test_forged_records_cannot_land_bytes``). What this
does NOT provide: confidentiality, or replay protection against an
on-path observer of the plaintext record stream — for that, deploy on
trusted segments or under an encrypted overlay, as with the reference's
NIC-bypassing RDMA.
"""

from __future__ import annotations

import hmac as _hmac
import os
import socket
import struct
import threading
import uuid
from typing import Dict, Optional, Tuple

from tpurpc.core.pair import MemoryDomain, Region, Window, register_domain
from tpurpc.utils.trace import TraceFlag

trace_tcpw = TraceFlag("tcpw")

#: record header: region key (16B), offset (u64), payload length (u32);
#: followed on the wire by a 16-byte truncated HMAC-SHA256 (header+payload,
#: per-region secret), then the payload
_REC = struct.Struct("<16sQI")
_MAC_LEN = 16
_HELLO = b"TPW2"  # protocol guard; bumped from TPWD when records grew MACs


def _record_mac(secret: bytes, hdr: bytes, payload) -> bytes:
    h = _hmac.new(secret, hdr, "sha256")
    h.update(payload)
    return h.digest()[:_MAC_LEN]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # recv_into a preallocated buffer: O(n) for ring-sized records (the
    # += accumulation alternative is O(n²) in copies at 64KB TCP chunks)
    buf = bytearray(n)
    view = memoryview(buf)
    filled = 0
    while filled < n:
        try:
            got = sock.recv_into(view[filled:])
        except OSError:
            return None
        if not got:
            return None
        filled += got
    return bytes(buf)


def _recv_discard(sock: socket.socket, n: int) -> bool:
    """Consume n stream bytes WITHOUT an n-sized allocation — for records
    that will be dropped anyway (unknown key, oversized length). The wire
    length field is attacker-controlled; allocating it before any
    authorization check would hand an unauthenticated connection a 4 GiB
    bytearray per record."""
    scratch = bytearray(min(n, 65536))
    view = memoryview(scratch)
    left = n
    while left:
        try:
            got = sock.recv_into(view[:min(left, len(scratch))])
        except OSError:
            return False
        if not got:
            return False
        left -= got
    return True


class _RecordServer:
    """Per-process applier: lands inbound one-sided writes into regions."""

    _instance: Optional["_RecordServer"] = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> "_RecordServer":
        with cls._lock:
            inst = cls._instance
            if inst is None or inst.pid != os.getpid():
                # Fork-aware: a child inherits the singleton object but NOT
                # its accept/applier threads — regions registered in the
                # child would advertise a port only the parent serves. Fresh
                # server (and peer-link cache) per process.
                _PeerLink.forget_inherited()
                inst = cls._instance = _RecordServer()
            return inst

    def __init__(self):
        from tpurpc.utils.config import get_config

        self.pid = os.getpid()
        #: key -> (region, per-region HMAC secret)
        self._regions: "Dict[bytes, Tuple[Region, bytes]]" = {}
        self._reg_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((get_config().tcpw_bind, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stopped = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="tpurpc-tcpw-accept").start()

    def close(self) -> None:
        """Stop accepting and release the port (process teardown/tests)."""
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass
        with type(self)._lock:
            if type(self)._instance is self:
                type(self)._instance = None

    # -- region registry -----------------------------------------------------

    def register(self, key: bytes, region: Region, secret: bytes) -> None:
        with self._reg_lock:
            self._regions[key] = (region, secret)

    def unregister(self, key: bytes) -> None:
        with self._reg_lock:
            self._regions.pop(key, None)

    # -- inbound -------------------------------------------------------------

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stopped:
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._apply_loop, args=(conn,),
                             daemon=True, name="tpurpc-tcpw-apply").start()

    def _apply_loop(self, conn: socket.socket) -> None:
        """One peer process's ordered write stream; applied sequentially —
        the in-order-delivery property the ring protocol's publication
        invariant (payload visible before seq words) rests on."""
        with conn:
            if _recv_exact(conn, len(_HELLO)) != _HELLO:
                trace_tcpw.log("record conn with bad hello; dropping")
                return
            # Budget for records that cannot be MAC-verified (unknown key,
            # oversized length): some are legit — writes racing region
            # teardown, the deregistered-MR analog, ~2 per closed
            # connection on this SHARED long-lived link — but an
            # unauthenticated attacker must not get to stream them forever
            # (or use them as a live-key oracle at zero cost). The budget
            # REPLENISHES on every verified record: a real peer's link
            # carries verified traffic between teardown bursts and never
            # dies (churn-soak proven at 150 connections), while an
            # attacker — who by definition cannot produce a verified
            # record — exhausts it and is dropped.
            BUDGET = 1024
            unverified_budget = BUDGET
            while True:
                hdr = _recv_exact(conn, _REC.size)
                if hdr is None:
                    return
                key, off, ln = _REC.unpack(hdr)
                mac = _recv_exact(conn, _MAC_LEN)
                if mac is None:
                    return
                with self._reg_lock:
                    entry = self._regions.get(key)
                # Authorization-before-allocation: the wire length is only
                # trusted up to the registered region's size; everything
                # else is skimmed through a bounded scratch and dropped.
                if entry is None or ln > len(entry[0].buf):
                    if not _recv_discard(conn, ln):
                        return
                    unverified_budget -= 1
                    trace_tcpw.log(
                        "discarding %dB unverifiable write (%s); budget %d",
                        ln, "dead region" if entry is None else "oversized",
                        unverified_budget)
                    if unverified_budget <= 0:
                        return
                    continue
                payload = _recv_exact(conn, ln)
                if payload is None:
                    return
                region, secret = entry
                if not _hmac.compare_digest(
                        mac, _record_mac(secret, hdr, payload)):
                    # Forged/garbage record: authorization is possession of
                    # the per-region SECRET (bootstrap-channel delivered),
                    # not the guessable-on-the-wire key. The sender is
                    # either an attacker or hopelessly desynced — drop the
                    # whole connection, land nothing.
                    trace_tcpw.log("record failed HMAC verification; "
                                   "dropping connection")
                    return
                unverified_budget = BUDGET  # verified: a real peer's link
                try:
                    buf = region.buf
                    if off + ln > len(buf):
                        trace_tcpw.log("discarding out-of-bounds write "
                                       "(%d+%d > %d)", off, ln, len(buf))
                        continue
                    buf[off:off + ln] = payload
                except ValueError:
                    # Region.close() releases the view BEFORE unregistering;
                    # a record landing in that window is a stale write
                    trace_tcpw.log("discarding %dB write to closing region",
                                   ln)
                    continue
                # Post-apply kick (Region.on_write): THIS is what makes the
                # async domain lose no wakeups — the peer's notify token can
                # arrive before this record does, and a waiter that re-checked
                # too early would sleep forever without it. (Teardown nulls
                # the hook before closing its wake fds, so a racing kick can
                # never write a reused fd.)
                hook = region.on_write
                if hook is not None:
                    try:
                        hook()
                    except Exception:
                        pass  # racing pair teardown


class _PeerLink:
    """One refcounted, ordered record connection to a peer process."""

    _links: Dict[Tuple[str, int], "_PeerLink"] = {}
    _links_lock = threading.Lock()
    _links_pid = os.getpid()

    @classmethod
    def forget_inherited(cls) -> None:
        """Post-fork: inherited link sockets belong to the parent's streams —
        reusing one would interleave two processes' records. Drop the cache
        (fds close with the objects; the parent's copies are unaffected)."""
        with cls._links_lock:
            cls._links.clear()
            cls._links_pid = os.getpid()

    @classmethod
    def attach(cls, host: str, port: int) -> "_PeerLink":
        with cls._links_lock:
            if cls._links_pid != os.getpid():
                cls._links.clear()
                cls._links_pid = os.getpid()
            link = cls._links.get((host, port))
            if link is None or link.dead:
                link = cls._links[(host, port)] = _PeerLink(host, port)
            link.refs += 1
            return link

    def __init__(self, host: str, port: int):
        self.key = (host, port)
        self.refs = 0
        self.dead = False
        self._sock = socket.create_connection((host, port), timeout=20)
        # connect timeout must NOT linger on the stream: a mid-record
        # socket.timeout would leave the shared ordered stream misaligned
        # (writes block on backpressure instead — that IS the flow control)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._sock.sendall(_HELLO)

    def write(self, key: bytes, off: int, data, secret: bytes) -> None:
        with self._send_lock:
            if self.dead:
                raise ConnectionError("tcp_window peer link closed")
            try:
                # gathered send per record (no concat copy); sendmsg may
                # stop short on backpressure, so finish the record with
                # sendall — the lock holds until the record is whole, which
                # is what keeps the shared stream parseable.
                view = memoryview(data).cast("B")
                hdr = _REC.pack(key, off, len(view))
                pre = hdr + _record_mac(secret, hdr, view)
                sent = self._sock.sendmsg([pre, view])
                if sent < len(pre):
                    self._sock.sendall(pre[sent:])
                    sent = len(pre)
                if sent < len(pre) + len(view):
                    self._sock.sendall(view[sent - len(pre):])
            except OSError:
                # any send failure may have transmitted a PARTIAL record:
                # the stream is misaligned beyond repair — poison the link
                # so no other window appends bytes the applier would parse
                # as a garbage header.
                self.dead = True
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise

    def release(self) -> None:
        with self._links_lock:
            self.refs -= 1
            if self.refs > 0:
                return
            self._links.pop(self.key, None)
            self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass


class TcpWindowDomain(MemoryDomain):
    """Socket-carried one-sided writes: the cross-host ring fabric."""

    kind = "tcp_window"

    def alloc(self, nbytes: int) -> Region:
        server = _RecordServer.get()
        key = uuid.uuid4().bytes
        # Write-authorization secret: travels ONLY inside the handle, i.e.
        # over the bootstrap channel (TLS-capable) — never on the record
        # stream. Possession of it is what lets a peer land bytes here.
        secret = os.urandom(32)
        buf = bytearray(nbytes)
        from tpurpc.utils.config import get_config

        handle = (f"tcpw:{get_config().tcpw_host}:{server.port}:"
                  f"{key.hex()}:{secret.hex()}")

        def _close():
            server.unregister(key)

        region = Region(handle, buf, _close)
        # registered as the Region itself: the applier lands bytes through
        # region.buf and runs its on_write kick (async-domain wakeup contract)
        server.register(key, region, secret)
        return region

    def open_window(self, handle: str, nbytes: int) -> Window:
        if not handle.startswith("tcpw:"):
            raise ValueError(f"not a tcp_window handle: {handle!r}")
        host, port_s, key_hex, secret_hex = handle[5:].rsplit(":", 3)
        key = bytes.fromhex(key_hex)
        secret = bytes.fromhex(secret_hex)
        link = _PeerLink.attach(host, int(port_s))

        def write(off: int, data) -> None:
            link.write(key, off, data, secret)

        # view=None: not host-addressable from this side (cross-host); the
        # pair's native fast paths check for None and stay on the portable
        # path (pair.py:568).
        return Window(write, link.release, view=None)


def _after_fork_in_child() -> None:
    """Fresh locks + empty singletons in the child: a thread holding any of
    these locks at fork() would leave the child a locked mutex with no
    owner (deadlock on first touch). Class locks are replaced; the
    INHERITED instance's/links' locks are replaced too — closures captured
    pre-fork (Region._close -> server.unregister, Window.write -> link)
    still reach those objects. Inherited links are also marked dead: their
    sockets belong to the parent's record streams, and a child write would
    interleave two processes' records."""
    inst = _RecordServer._instance
    if inst is not None:
        inst._reg_lock = threading.Lock()
    _RecordServer._lock = threading.Lock()
    _RecordServer._instance = None
    for link in _PeerLink._links.values():
        link._send_lock = threading.Lock()
        link.dead = True
    _PeerLink._links_lock = threading.Lock()
    _PeerLink._links = {}
    _PeerLink._links_pid = os.getpid()


os.register_at_fork(after_in_child=_after_fork_in_child)

register_domain("tcp_window", TcpWindowDomain)
