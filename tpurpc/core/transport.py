"""The cross-process transport seam (tpurpc-simnet, ISSUE 17).

Every message a tpurpc process sends to ANOTHER process — a framed
control op, a descriptor-ring doorbell store, a one-sided window write,
an fd kick — funnels through :func:`dispatch`. Outside an active
simulation the seam is one global ``None``-check and a direct call:
byte-identical behavior, no allocation, nothing observable.

Under :mod:`tpurpc.analysis.simnet` the hook intercepts each dispatch
and turns it into a *scheduler pick*: delivery order, bounded delay,
per-link partitions, and node-crash-at-this-message-point all become
explorable choices of the deterministic schedule explorer (the exact
analog of the PR 12 lock-factory hook, one layer up the stack).

The seam's contract, enforced structurally by the ``xproc`` lint rule:

* Protocol logic in the cross-process modules (``rendezvous.py``,
  ``ctrlring.py``, ``disagg.py``, the pair notify path) calls
  ``dispatch(point, obj, fn, *args)`` instead of invoking the raw
  send/store/kick directly.
* ``point`` names the message class — ``"frame"`` (a framed/socket
  control message), ``"post"`` (a descriptor-ring slot store), ``"write"``
  (a one-sided window landing), ``"kick"`` (an fd doorbell).
* ``obj`` identifies the emitting protocol object (the hook routes by
  it); ``fn(*args, **kw)`` performs the real I/O when the hook declines
  or is absent.
* The hook returns ``NotImplemented`` to decline (the seam then calls
  ``fn`` directly) or any other value to claim the dispatch — typically
  after enqueuing ``fn`` for later in-order delivery on a simulated
  link. A claimed ``"frame"`` dispatch must return a truthy value where
  the caller checks delivery (``Pair._send_frame``).

The hook is process-global and installation is not thread-safe by
design: simulations install it before spawning scenario tasks and clear
it after joining them, exactly like ``set_factory_hook``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["set_transport_hook", "transport_hook", "dispatch"]

#: ``hook(point, obj, fn, args, kwargs)`` -> ``NotImplemented`` to
#: decline, anything else to claim the dispatch. ``None`` = no sim.
_hook: Optional[Callable[..., Any]] = None


def set_transport_hook(hook: Optional[Callable[..., Any]]) -> None:
    """Install (or clear, with ``None``) the simulation transport hook."""
    global _hook
    _hook = hook


def transport_hook() -> Optional[Callable[..., Any]]:
    return _hook


def dispatch(point: str, obj: Any, fn: Callable[..., Any],
             *args: Any, **kwargs: Any) -> Any:
    """Route one cross-process message emission through the seam.

    ``fn(*args, **kwargs)`` is the real emission (socket send, ring slot
    store, window write loop, fd kick); with no hook installed — the
    production path — that call happens immediately and its value is
    returned unchanged.
    """
    h = _hook
    if h is not None:
        r = h(point, obj, fn, args, kwargs)
        if r is not NotImplemented:
            return r
    return fn(*args, **kwargs)
