"""Pair: one high-performance connection — two receive rings + a status word, glued by
one-sided writes.

Reference: ``src/core/lib/ibverbs/pair.{h,cc}`` (``PairPollable``).  A pair owns

* a **receive ring** the peer writes messages into (data moves by one-sided writes into
  the peer's ring at the mirrored tail — ``pair.cc:587-622`` ``postWrite``),
* a 16-byte **status buffer** ``{remote_head, peer_exit}`` the peer writes credits and
  the graceful-close flag into (``pair.h:100-103``),
* the six-state lifecycle ``kUninitialized → kInitialized → kConnected →
  kHalfClosed/kDisconnected/kError`` (``pair.h:44-51``), with ``init()`` explicitly
  reviving error/disconnected pairs for pool reuse (``pair.cc:85-141``).

Where the reference's one-sided write is an ``IBV_WR_RDMA_WRITE`` on an RC queue pair,
tpurpc abstracts it as a :class:`MemoryDomain` — in-process buffers for loopback,
POSIX shared memory for cross-process on one host, and a device-staged domain for the
TPU HBM ring (``tpurpc.tpu``).  The *protocol* (framing, credits, close, liveness) is
identical across domains, which is the property the reference proves by running three
different NIC disciplines over one ring format.

Bootstrap mirrors the reference exactly: a boring already-connected socket carries the
address exchange (``exchange_data``, ``rdma_bp_posix.cc:640-692``), after which the
socket is *kept* as the event/liveness channel — the reference keeps its TCP fd for
liveness too (``rdma_conn.h:90-99`` ``IsPeerAlive``) and delivers completion interrupts
via completion-channel fds (``rdma_conn.cc:24-26``); our notify socket plays both roles.
"""

from __future__ import annotations

import contextlib
import ctypes
import enum
import json
import os
import socket
import ssl
import struct
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpurpc.analysis.locks import make_lock
from tpurpc.core import _native
from tpurpc.core import transport as _transport
from tpurpc.obs import flight as _flight
from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.obs import tracing as _tracing
from tpurpc.tpu import ledger as ring_ledger
from tpurpc.core.ring import (RingCorruption, RingReader, RingWriter,
                              _BYTES_OUT, _MSGS_OUT,
                              truncate_after_read as ring_truncate)
from tpurpc.utils import stats as _stats
from tpurpc.utils.config import get_config

# tpurpc-scope fleet gauges (ISSUE 4): evaluated at scrape time over the
# weakly-referenced live pairs — send-credit stalls and connection counts
# become visible on a live process with zero hot-path cost.
_PAIRS_CONNECTED = _metrics.fleet(
    "pairs_connected", lambda p: 1.0 if p.state.name == "CONNECTED" else 0.0)
_PAIRS_WRITE_STALLED = _metrics.fleet(
    "pairs_write_stalled",
    # CONNECTED only: a pair that died MID-STALL keeps want_write set while
    # anything still references it, and a dead pair's stall is not evidence
    # — the watchdog would keep attributing live calls to credit-starvation
    # long after the wedged peer was torn down (tpurpc-fleet, ISSUE 6)
    lambda p: 1.0 if (p.want_write and p.state.name == "CONNECTED")
    else 0.0)
# tpurpc-blackbox (ISSUE 5): a CONNECTED pair with a complete message
# sitting undrained — the watchdog's poller-wake-latency evidence. Scrape/
# sweep-time only; has_message is a header peek (native scan when built).
_PAIRS_MSG_WAITING = _metrics.fleet(
    "pairs_msg_waiting",
    lambda p: 1.0 if (p.state.name == "CONNECTED" and p.has_message())
    else 0.0)
# tpurpc-hive (ISSUE 16): the connection-scale plane. A parked pair holds
# no ring regions and no poller slot — just the notify socket and a stub —
# and the per-connection resident estimate is what the C100K bench curves
# report per ramp stage.
_PAIRS_PARKED = _metrics.fleet(
    "pairs_parked", lambda p: 1.0 if p._parked else 0.0)
_PAIR_RESIDENT = _metrics.fleet(
    "pair_resident_bytes_est", lambda p: float(p.resident_bytes_est()))
from tpurpc.utils.trace import trace_ring

# tpurpc-lens (ISSUE 8): the `wire` waterfall hop is the transport
# boundary — on this plane, Pair.send's one-sided placement (credit fold,
# chunking and ring encode included). The fused native send bypasses
# RingWriter, so its bytes land in the send_ring hop here too.
_LENS_WIRE_BYTES, _LENS_WIRE_NS, _LENS_WIRE_COPY = _lens.hop_counters("wire")
_LENS_SR_BYTES, _LENS_SR_NS, _LENS_SR_COPY = _lens.hop_counters("send_ring")

_LENS_STAGES = {
    "send": "pair-send",
    "_send_inner": "pair-send",
    "_send_fast": "pair-send",
    "recv_into": "ring-read",
    "recv": "ring-read",
    "spin": "poller-wait",
}
_profiler.register_stages(__file__, _LENS_STAGES)

_U64 = struct.Struct("<Q")

#: Status region layout. Two cache lines: the first holds the PEER-written
#: words (credit head, peer_exit — one-sided writes from the other side), the
#: second holds the LOCALLY-written waiter-advertisement words the peer only
#: reads. Separate lines so peer credit writes and local waiting-flag stores
#: never false-share (cross-process cache-line ping-pong on the hot path).
STATUS_BYTES = 128
_STATUS_HEAD_OFF = 0
_STATUS_EXIT_OFF = 8
#: "a read-waiter is blocked on the notify fd" — senders skip the notify
#: syscall when 0 (receiver is spinning or mid-drain). Futex-style protocol;
#: fences + proof in native/src/ring.cc tpr_store_u64_seqcst.
_STATUS_RXWAIT_OFF = 64
#: same, for a credit-stalled writer blocked on the notify fd
_STATUS_WXWAIT_OFF = 72
_WAIT_OFF = {"read": _STATUS_RXWAIT_OFF, "write": _STATUS_WXWAIT_OFF}


class PairState(enum.Enum):
    """Mirrors ``PairStatus`` (``pair.h:44-51``)."""

    UNINITIALIZED = "uninitialized"
    INITIALIZED = "initialized"
    CONNECTED = "connected"
    HALF_CLOSED = "half_closed"      # peer wrote peer_exit and stopped sending
    DISCONNECTED = "disconnected"
    ERROR = "error"


# ---------------------------------------------------------------------------
# Memory domains: who implements the one-sided write.
# ---------------------------------------------------------------------------

def retry_buffer_op(fn: Callable[[], None], timeout_s: float = 2.0) -> None:
    """Run a release/unmap that may transiently hit BufferError while a
    GIL-free native spin holds an exported view (≤ one bounded slice)."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fn()
            return
        except BufferError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.001)


class Region:
    """A chunk of registerable memory owned by this side (ref: ``Buffer``,
    ``buffer.h:12-35`` — pinned + ibv_reg_mr there; here just addressable bytes)."""

    __slots__ = ("handle", "buf", "_close", "on_write")

    def __init__(self, handle: str, buf, close: Callable[[], None] = lambda: None):
        self.handle = handle
        self.buf = memoryview(buf)
        self._close = close
        #: Optional post-apply hook for ASYNCHRONOUS domains (tcp_window):
        #: called by the domain's applier after landing peer bytes in this
        #: region. Synchronous domains (local/shm) never call it — their
        #: writes are visible before the peer's notify token can arrive, so
        #: the token alone is a sufficient wakeup. With an async domain the
        #: token (notify socket) can BEAT the data (record socket); the
        #: applier's kick is what closes that lost-wakeup window.
        self.on_write: Optional[Callable[[], None]] = None

    def close(self) -> None:
        # A GIL-free native spin (Pair.spin) may still pin this memory through
        # an exported buffer view for ≤ one bounded spin slice; BOTH the
        # memoryview release and the shm unmap refuse while exports exist.
        # Retry briefly instead of leaking (the spinner unpins within one
        # bounded slice).
        retry_buffer_op(self.buf.release)
        retry_buffer_op(self._close)


class Window:
    """A write handle onto the *peer's* region (ref: ``MemoryRegion`` envelope shipping
    an ``ibv_mr`` descriptor, ``memory_region.h:14-47``)."""

    __slots__ = ("write", "view", "_close")

    def __init__(self, write: Callable[[int, bytes], None],
                 close: Callable[[], None] = lambda: None,
                 view: "Optional[memoryview]" = None):
        self.write = write  # write(offset, data) — one-sided, no peer CPU involved
        self.view = view    # mapped memory when host-addressable (native path)
        self._close = close

    def close(self) -> None:
        self._close()


class MemoryDomain:
    """Allocates local regions and opens windows onto peer regions by handle."""

    kind = "abstract"

    def alloc(self, nbytes: int) -> Region:
        raise NotImplementedError

    def open_window(self, handle: str, nbytes: int) -> Window:
        raise NotImplementedError


class LocalDomain(MemoryDomain):
    """In-process domain: regions live in a process-wide registry; windows write
    directly.  This is the "loopback PairPollable" the reference never wrote
    (SURVEY.md §4 calls it the missing fake) — it lets the full pair/poller/endpoint
    stack run in CI with zero hardware."""

    kind = "local"
    _registry: Dict[str, bytearray] = {}
    _lock = make_lock("LocalDomain._lock")

    def alloc(self, nbytes: int) -> Region:
        handle = f"local:{uuid.uuid4().hex}"
        buf = bytearray(nbytes)
        with self._lock:
            self._registry[handle] = buf

        def _close():
            with self._lock:
                self._registry.pop(handle, None)

        return Region(handle, buf, _close)

    def open_window(self, handle: str, nbytes: int) -> Window:
        with self._lock:
            buf = self._registry[handle]
        mv = memoryview(buf)

        def write(off: int, data) -> None:
            mv[off:off + len(data)] = data

        return Window(write, mv.release, view=mv)


class ShmDomain(MemoryDomain):
    """Cross-process domain over POSIX shared memory: a server and its local clients
    exchange ring writes through ``/dev/shm`` with zero kernel involvement per
    message — the closest host-only analog of the reference's NIC-placed writes."""

    kind = "shm"

    # The allocator owns unlink explicitly (Region.close); Python's
    # resource_tracker would otherwise unlink from every process that ever
    # mapped the segment. Unregistering after the fact still races (processes
    # sharing one inherited tracker each send UNREGISTER → KeyError spam in the
    # tracker daemon), so suppress the registration itself. Python 3.13 has
    # SharedMemory(track=False); this is the 3.12 equivalent.
    _track_mu = make_lock("ShmDomain._track_mu")

    @staticmethod
    @contextlib.contextmanager
    def _untracked():
        from multiprocessing import resource_tracker

        with ShmDomain._track_mu:
            orig_reg = resource_tracker.register
            orig_unreg = resource_tracker.unregister

            def _skip_reg(name, rtype):
                if rtype != "shared_memory":
                    orig_reg(name, rtype)

            def _skip_unreg(name, rtype):
                if rtype != "shared_memory":
                    orig_unreg(name, rtype)

            resource_tracker.register = _skip_reg
            resource_tracker.unregister = _skip_unreg
            try:
                yield
            finally:
                resource_tracker.register = orig_reg
                resource_tracker.unregister = orig_unreg

    def alloc(self, nbytes: int) -> Region:
        from multiprocessing import shared_memory

        with self._untracked():
            shm = shared_memory.SharedMemory(create=True, size=nbytes)

        def _close():
            shm.close()
            with self._untracked():  # unlink() also talks to the tracker
                try:
                    shm.unlink()
                except FileNotFoundError:
                    pass

        return Region(f"shm:{shm.name}", shm.buf, _close)

    def open_window(self, handle: str, nbytes: int) -> Window:
        from multiprocessing import shared_memory

        assert handle.startswith("shm:")
        with self._untracked():
            shm = shared_memory.SharedMemory(name=handle[4:])
        mv = shm.buf

        def write(off: int, data) -> None:
            mv[off:off + len(data)] = data

        def _close():
            mv.release()
            shm.close()

        return Window(write, _close, view=mv)


_DOMAINS: Dict[str, Callable[[], MemoryDomain]] = {
    "local": LocalDomain,
    "shm": ShmDomain,
}

#: domains that register themselves on first import — tcp_window because
#: its import starts background machinery (a record server), verbs (the
#: RDMA-NIC skeleton) because construction raises a clear RuntimeError
#: where libibverbs is unavailable
_LAZY_DOMAINS = {"tcp_window": "tpurpc.core.tcpw",
                 "verbs": "tpurpc.core.verbs"}


def register_domain(kind: str, factory: Callable[[], MemoryDomain]) -> None:
    """Extension point the TPU domain uses (``tpurpc.tpu``)."""
    _DOMAINS[kind] = factory


def make_domain(kind: str) -> MemoryDomain:
    """Instantiate a registered domain by name (the ``TPURPC_RING_DOMAIN``
    dispatch). ``tcp_window`` registers lazily on first use — it is the only
    domain whose import starts background machinery (a record server)."""
    if kind not in _DOMAINS and kind in _LAZY_DOMAINS:
        import importlib

        importlib.import_module(_LAZY_DOMAINS[kind])  # registers itself
    factory = _DOMAINS.get(kind)
    if factory is None:
        raise ValueError(f"unknown ring domain {kind!r} "
                         f"(have {sorted(_DOMAINS)})")
    # call OUTSIDE the lookup guard: a KeyError raised inside a registered
    # factory must surface as itself, not as "unknown ring domain"
    return factory()


# ---------------------------------------------------------------------------
# Shared ring-region pool (tpurpc-hive, ISSUE 16).
# ---------------------------------------------------------------------------

_POOL_LEASED_BYTES = _metrics.gauge("ring_pool_leased_bytes")
_POOL_FREE_BYTES = _metrics.gauge("ring_pool_free_bytes")


class RingPool:
    """Process-wide free list of ring/status regions keyed by
    ``(domain kind, byte size)`` — the RDMAvisor-style shared resource pool
    that lets 50k mostly-idle pairs multiplex O(size-classes) ring
    allocations instead of pinning one ring each.

    Safety invariant: a region may enter the free list ONLY once no peer
    window onto it can still write.  ``Pair.init`` forbids region reuse
    within a connection exactly because a stale one-sided writer could land
    bytes in the next tenant's ring; the park handshake's ACK (the peer
    confirming it closed its windows) is the proof that makes cross-pair
    reuse safe here.  Free regions are zeroed before shelving so a fresh
    :class:`~tpurpc.core.ring.RingReader` can never misparse a previous
    tenant's frame headers as live messages.

    Only plain host-memory domains are pooled; device/NIC-bound regions
    (verbs QPs, tcp_window applier bindings) pass through to alloc/close so
    their peer-specific state is never handed to a different pair.
    """

    _instance: "Optional[RingPool]" = None
    _instance_lock = make_lock("RingPool._instance_lock")

    #: lock map, checked by `python -m tpurpc.analysis` (lint rule `lock`)
    _GUARDED_BY = {"_free": "_lock", "_free_bytes": "_lock",
                   "_out": "_lock", "_instance": "_instance_lock"}

    _POOLABLE = frozenset({"local", "shm"})
    _MAX_FREE_BYTES = 256 << 20
    _MAX_FREE_PER_CLASS = 4096

    @classmethod
    def get(cls) -> "RingPool":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = RingPool()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.drain()

    def __init__(self):
        self._free: Dict[Tuple[str, int], List[Region]] = {}
        self._free_bytes = 0
        #: id(region) -> nbytes for regions handed out by lease() — release
        #: of a region the pool never leased (a pair's original init()
        #: allocation entering the pool at first park) must not drive the
        #: leased gauge negative
        self._out: Dict[int, int] = {}
        self._lock = make_lock("RingPool._lock")

    def lease(self, domain: MemoryDomain, nbytes: int) -> Region:
        """Hand out a writer-free region of exactly ``nbytes`` — recycled
        from the free list when the size class has one, freshly allocated
        otherwise.  Callers MUST pair every lease with a :meth:`release` on
        their failure paths (lint rule ``ringpool``)."""
        key = (domain.kind, nbytes)
        region = None
        with self._lock:
            bucket = self._free.get(key)
            if bucket:
                region = bucket.pop()
                self._free_bytes -= nbytes
        if region is None:
            region = domain.alloc(nbytes)
            _stats.counter_inc("ring_pool_alloc")
        else:
            _stats.counter_inc("ring_pool_hit")
        with self._lock:
            self._out[id(region)] = nbytes
            _POOL_LEASED_BYTES.set(float(sum(self._out.values())))
            _POOL_FREE_BYTES.set(float(self._free_bytes))
        return region

    def release(self, region: Optional[Region]) -> None:
        """Return a region to the free list (or close it when the domain
        isn't poolable / the list is full).  The caller asserts the pool
        invariant: no peer window onto this region can still write."""
        if region is None:
            return
        region.on_write = None
        try:
            nbytes = len(region.buf)
        except ValueError:
            nbytes = 0  # already released; nothing to pool
        kind = region.handle.split(":", 1)[0]
        with self._lock:
            self._out.pop(id(region), None)
            poolable = (nbytes > 0 and kind in self._POOLABLE
                        and self._free_bytes + nbytes <= self._MAX_FREE_BYTES
                        and len(self._free.get((kind, nbytes), ()))
                        < self._MAX_FREE_PER_CLASS)
        if poolable:
            try:
                # zero before shelving: the next tenant's reader starts at
                # head 0 and must never see this tenant's frame headers
                np.frombuffer(region.buf, dtype=np.uint8).fill(0)
            except (ValueError, TypeError):
                poolable = False
        if not poolable:
            try:
                region.close()
            except Exception:
                pass
            with self._lock:
                _POOL_LEASED_BYTES.set(float(sum(self._out.values())))
            return
        with self._lock:
            self._free.setdefault((kind, nbytes), []).append(region)
            self._free_bytes += nbytes
            _POOL_LEASED_BYTES.set(float(sum(self._out.values())))
            _POOL_FREE_BYTES.set(float(self._free_bytes))

    def forget(self, region: Optional[Region]) -> None:
        """Drop lease accounting for a region its owner is closing directly
        — teardown paths where the region must NOT re-enter the free list
        (no peer window-close ack exists, so the pool invariant is unproven)."""
        if region is None:
            return
        with self._lock:
            if self._out.pop(id(region), None) is not None:
                _POOL_LEASED_BYTES.set(float(sum(self._out.values())))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"free_bytes": self._free_bytes,
                    "free_regions": sum(len(b) for b in self._free.values()),
                    "leased_bytes": sum(self._out.values()),
                    "leased_regions": len(self._out)}

    def drain(self) -> None:
        with self._lock:
            regions = [r for b in self._free.values() for r in b]
            self._free.clear()
            self._free_bytes = 0
            _POOL_FREE_BYTES.set(0.0)
        for r in regions:
            try:
                r.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Address: what gets exchanged at bootstrap.
# ---------------------------------------------------------------------------

class Address:
    """Serializable rendezvous blob (ref: ``Address`` with lid/qpn/psn/gid/tag/
    ring_buffer_size, ``address.h:24-31``; peers assert tag+size match,
    ``pair.cc:148-149``)."""

    def __init__(self, tag: str, domain_kind: str, ring_size: int,
                 ring_handle: str, status_handle: str,
                 caps: "Optional[Sequence[str]]" = None):
        self.tag = tag
        self.domain_kind = domain_kind
        self.ring_size = ring_size
        self.ring_handle = ring_handle
        self.status_handle = status_handle
        #: capability strings, negotiated at bootstrap. "waitflag" = this side
        #: publishes the waiter-advertisement words (native fences present),
        #: so its peer may skip notify bytes when no waiter is advertised.
        #: A peer that doesn't advertise it (TPURPC_NATIVE=0, older version)
        #: gets unconditional notifies — asymmetric processes never lose
        #: wakeups (reviewer finding: the skip must be opt-in per peer).
        self.caps = frozenset(caps or ())

    def to_bytes(self) -> bytes:
        return json.dumps({
            "tag": self.tag,
            "domain": self.domain_kind,
            "ring_size": self.ring_size,
            "ring": self.ring_handle,
            "status": self.status_handle,
            "caps": sorted(self.caps),
        }).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Address":
        d = json.loads(raw.decode())
        return cls(d["tag"], d["domain"], d["ring_size"], d["ring"],
                   d["status"], d.get("caps", ()))


#: Bootstrap frame magic.  A peer whose GRPC_PLATFORM_TYPE disagrees (e.g. a TCP
#: client hitting a ring server) sends arbitrary bytes here; the magic check turns
#: that misconfiguration into an immediate clear error instead of a hang.  (The
#: reference has no such guard — mismatched env vars are undefined behavior there.)
_BOOTSTRAP_MAGIC = b"TRB1"
_MAX_BLOB = 1 << 16
#: Bound on the address-exchange handshake (ref exchange_data poll loop is also
#: bounded, rdma_bp_posix.cc:640-692).
BOOTSTRAP_TIMEOUT_S = 20.0


def _send_blob(sock: socket.socket, blob: bytes) -> None:
    sock.sendall(_BOOTSTRAP_MAGIC + struct.pack("<I", len(blob)) + blob)


def _recv_blob(sock: socket.socket, preread: bytes = b"") -> bytes:
    magic = preread + _recv_exact(sock, 4 - len(preread))
    if magic != _BOOTSTRAP_MAGIC:
        raise ConnectionError(
            f"bad bootstrap magic {magic!r}: peer is not speaking the ring "
            f"bootstrap protocol (GRPC_PLATFORM_TYPE mismatch between peers?)")
    need = struct.unpack("<I", _recv_exact(sock, 4))[0]
    if need > _MAX_BLOB:
        raise ConnectionError(f"bootstrap blob implausibly large ({need} bytes)")
    return _recv_exact(sock, need)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed during address exchange")
        out += chunk
    return out


def peek_protocol(sock: socket.socket, timeout: float = BOOTSTRAP_TIMEOUT_S
                  ) -> bytes:
    """Server-side protocol dispatch: consume and return the first 4 bytes.

    A ring-platform listener uses this to route each accepted connection —
    ring clients open with the TRB1 bootstrap magic; stock gRPC (h2 preface)
    and native-TCP-framing clients get a TCP endpoint carrying the preread
    bytes instead of a bootstrap error. Works identically on TLS sockets
    (the bytes are post-decryption), which MSG_PEEK cannot."""
    old = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        return _recv_exact(sock, 4)
    finally:
        try:
            sock.settimeout(old)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# The Pair.
# ---------------------------------------------------------------------------

#: notify tokens carried on the notify socket (≈ completion events / WRITE_WITH_IMM)
NOTIFY_DATA = b"d"
NOTIFY_CREDIT = b"c"
NOTIFY_EXIT = b"x"
#: tpurpc-hive park-protocol tokens (same stream; see Pair.maybe_park).
#: PARK asks the peer to close its one-sided windows into our regions and
#: answer ACK — only that ack proves no stale writer remains, which is THE
#: invariant letting the regions enter the shared RingPool despite init()'s
#: always-fresh rule. NACK aborts (peer mid-send). WAKE asks a parked peer
#: to re-arm because we have bytes for it; REARM prefixes a framed Address
#: blob advertising fresh (or, on a park abort, retained) rings.
NOTIFY_PARK = b"p"
NOTIFY_PARK_ACK = b"q"
NOTIFY_PARK_NACK = b"n"
NOTIFY_WAKE = b"w"
#: "r" = re-arm onto FRESHLY LEASED rings (unpark): the peer builds a writer
#: at position zero. "R" = re-arm onto RETAINED rings (park abort / repair):
#: the peer restores its snapshotted writer position. The distinction must
#: ride the frame itself — the RingPool can hand the SAME region straight
#: back to the same pair, so handle identity cannot tell a fresh lease from
#: retained rings (observed: a recycled handle made the peer restore a stale
#: tail against a zeroed ring, black-holing the first post-unpark send).
NOTIFY_REARM = b"r"
NOTIFY_REARM_KEEP = b"R"
_CLASSIC_TOKENS = b"dcx"


class _ParkBusy(Exception):
    """Raised inside the send guard when a park episode owns the write side.
    Internal control flow only: ``Pair.send`` catches it, resolves the episode
    OUTSIDE the guard (strict lock order: _park_lock before _send_guard), and
    retries — callers never see it."""


class ContentAssertion:
    """Single-entrant tripwire on send/recv, like the reference's reentrancy guard
    (``pair.h:64-81``): two threads inside Send (or Recv) concurrently is a caller bug
    we want to explode loudly, not corrupt a ring.

    The park protocol's handlers (window close, re-arm, park initiation) also
    need the guard — they mutate the same side — but they run on the DRAIN or
    poller thread, not the caller's: a legitimate send/recv racing one of them
    is NOT a caller bug.  ``maintenance()`` entry marks the occupancy so the
    regular entry raises the retryable :class:`_ParkBusy` instead of the
    tripwire (found by schedule exploration: a sender crashed with the
    concurrent-entry AssertionError while the peer's park request was being
    handled)."""

    def __init__(self, name: str):
        self._name = name
        self._flag = False
        self._maint = False
        self._lock = make_lock(f"ContentAssertion[{name}]._lock")

    def __enter__(self):
        with self._lock:
            if self._flag:
                if self._maint:
                    raise _ParkBusy
                raise AssertionError(f"concurrent entry into {self._name}")
            self._flag = True

    def __exit__(self, *exc):
        with self._lock:
            self._flag = False
            self._maint = False
        return False

    @contextlib.contextmanager
    def maintenance(self):
        """Guard entry for a park-protocol handler: excludes an in-flight
        send/recv exactly like regular entry (AssertionError on conflict —
        the handler aborts or NACKs), but marks the hold so a racing
        REGULAR entrant gets the retryable :class:`_ParkBusy`."""
        with self._lock:
            if self._flag:
                raise AssertionError(f"concurrent entry into {self._name}")
            self._flag = True
            self._maint = True
        try:
            yield self
        finally:
            self.__exit__()


class Pair:
    """One connection's data plane.  Thread model: one sender thread + one receiver
    thread at a time (enforced by :class:`ContentAssertion`), any thread may poll."""

    def __init__(self, domain: Optional[MemoryDomain] = None,
                 ring_size: Optional[int] = None, tag: Optional[str] = None):
        cfg = get_config()
        self.domain = domain or LocalDomain()
        self.ring_size = ring_size or cfg.ring_buffer_size
        self.tag = tag or uuid.uuid4().hex[:12]
        self.state = PairState.UNINITIALIZED
        self.error: Optional[str] = None

        self.recv_region: Optional[Region] = None
        self.status_region: Optional[Region] = None
        self.reader: Optional[RingReader] = None
        self.writer: Optional[RingWriter] = None
        self._peer_ring: Optional[Window] = None
        self._peer_status: Optional[Window] = None

        #: peer-driven event channel (completion interrupts + liveness); set at connect
        self.notify_sock: Optional[socket.socket] = None
        #: local wakeup pipes (BPEV's grpc_wakeup_fd, pair.h:187) — ONE PER
        #: WAITER ROLE. The notify socket is shared and its tokens are
        #: consumed by whichever waiter drains first; a per-role pipe that
        #: only its own waiter consumes is what makes the kick-after-drain
        #: broadcast lossless (a reader eating a credit token re-kicks both
        #: pipes; the writer's pipe byte can only be consumed by the writer).
        self._wake_r: Dict[str, int] = {"read": -1, "write": -1}
        self._wake_w: Dict[str, int] = {"read": -1, "write": -1}
        #: persistent per-role selectors (epoll fd reused across waits — a
        #: fresh DefaultSelector per wait is 5 syscalls of pure overhead on
        #: the small-RPC path)
        self._selectors: Dict[str, object] = {}
        #: cached (np array, address) pins of the status pages for the
        #: waiter-advertisement words; nulled by teardown before any close
        self._status_np = None
        self._peer_status_np = None
        #: peer capability strings from the bootstrap Address (see Address.caps)
        self.peer_caps: frozenset = frozenset()

        self._send_guard = ContentAssertion("Pair.send")
        self._recv_guard = ContentAssertion("Pair.recv")
        self._credit_lock = make_lock("Pair._credit_lock")
        self._published_head_mirror = 0  # last head value we published to the peer
        self.want_write = False  # a sender is stalled waiting for credits
        #: adaptive-BPEV activity score (see tpurpc/core/poller.py EWMA
        #: constants): 1.0 = hot (waiters busy-poll), decays toward 0 on
        #: spin misses so idle pairs park on fds without spinning first
        self.activity_ewma = 1.0
        # monotonic counters (ref: per-pair live counters, pair.h:235-270)
        self.total_sent = 0
        self.total_recv = 0

        # serializes notify-socket writes (single-byte tokens AND the
        # multi-byte re-arm frame — an interleaved token inside a frame
        # would corrupt the peer's stream parser)
        self._notify_lock = make_lock("Pair._notify_lock")
        # tpurpc-hive (ISSUE 16): idle-pair parking. Lock order where both
        # are held: _park_lock BEFORE _send_guard (the park-request handler
        # takes them in that order; send paths check the park flags inside
        # the guard and RETRY outside it, never acquiring _park_lock under
        # the guard).
        self._park_lock = make_lock("Pair._park_lock")
        #: serializes drain_notifications end to end so the park-protocol
        #: parser sees the token stream in order (two waiters recv'ing
        #: concurrently would otherwise interleave a framed re-arm blob)
        self._drain_mu = make_lock("Pair._drain_mu")
        self._parked = False          # own regions pooled; ~stub remains
        self._park_pending = False    # PARK sent, ack/nack not yet seen
        self._park_sent_at = 0.0
        self._peer_parked = False     # peer's regions gone; writer is None
        #: (peer ring handle, tail, seq, remote_head) snapshot taken when a
        #: peer's PARK closes our writer — restored verbatim if the peer
        #: aborts the park and re-arms with the SAME rings
        self._saved_wstate: Optional[Tuple[str, int, int, int]] = None
        self._peer_ring_handle = ""
        self._notify_buf = b""        # partial re-arm frame reassembly
        self.last_activity = time.monotonic()
        self.parked_epochs = 0        # completed park->unpark round trips
        #: tpurpc-blackbox: interned flight-recorder tag (ints on the hot
        #: path) + open credit-starvation edge + adaptive-poll mode, all
        #: edge-triggered so a healthy pair emits nothing per message
        self._ftag = _flight.tag_for("pair:" + self.tag)
        self._starve_open = False
        self._flight_mode = "bp"
        _PAIRS_CONNECTED.track(self)
        _PAIRS_WRITE_STALLED.track(self)
        _PAIRS_MSG_WAITING.track(self)
        _PAIRS_PARKED.track(self)
        _PAIR_RESIDENT.track(self)

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> None:
        """Allocate fresh rings, reset counters.  Revives ERROR/DISCONNECTED/
        quiesced pairs like the reference (``pair.cc:85-141``, explicitly
        re-initializing recycled pool pairs).

        Regions are always NEW allocations (new shm name), never zero-and-reuse:
        a previous peer that still holds a window onto the old region (its sender
        racing past a state check at disconnect time) must land its stale
        one-sided writes in the orphaned segment, not in the next connection's
        ring.  The reference gets this for free because tearing down the QP kills
        in-flight RDMA; a shm window has no such fence."""
        self._release_channels()
        self._release_regions()
        self.recv_region = self.domain.alloc(self.ring_size)
        self.status_region = self.domain.alloc(STATUS_BYTES)
        # Async-domain wakeup closure (see Region.on_write): data landing in
        # the ring wakes readers; credits/exit landing in the status page
        # wake stalled writers. kick() is idempotent and cheap (pipe byte).
        self.recv_region.on_write = self.kick
        self.status_region.on_write = self.kick
        self.reader = RingReader(self.recv_region.buf, self.ring_size)
        self.writer = None  # created at connect, once peer ring size is known
        self._published_head_mirror = 0
        self.error = None
        self.want_write = False
        self.activity_ewma = 1.0  # recycled pairs start hot like fresh ones
        # hive park state never survives a re-init (fresh connection)
        self._parked = False
        self._park_pending = False
        self._peer_parked = False
        self._saved_wstate = None
        self._notify_buf = b""
        self.last_activity = time.monotonic()
        for role in ("read", "write"):
            r, w = os.pipe()
            os.set_blocking(r, False)
            os.set_blocking(w, False)
            self._wake_r[role] = r
            self._wake_w[role] = w
        self.state = PairState.INITIALIZED

    def local_address(self) -> Address:
        assert self.state in (PairState.INITIALIZED, PairState.CONNECTED)
        caps = ["waitflag"] if _native.load() is not None else []
        # tpurpc-express (ISSUE 9): advertise the rendezvous capability in
        # the bootstrap blob — a ring-plane connection then arms its bulk
        # plane at CONNECT TIME (core/rendezvous.py), with no hello round
        # trip to race the first big payload. Import-cycle-free probe: the
        # env gate lives in the rendezvous module, but pair must not
        # import it (rendezvous imports pair), so read the switch directly.
        if os.environ.get("TPURPC_RENDEZVOUS", "1").lower() not in (
                "0", "off", "false"):
            caps.append("rdv")
        # tpurpc-hive (ISSUE 16): park is a two-sided protocol — the peer
        # must ack the window-close and honor WAKE/REARM. Advertise it so
        # maybe_park never initiates against a peer that cannot answer
        # (the native C loop bootstraps its own Address without this cap;
        # a park request to it would retry forever and never complete).
        caps.append("park")
        return Address(self.tag, self.domain.kind, self.ring_size,
                       self.recv_region.handle, self.status_region.handle,
                       caps=caps)

    def connect_over_socket(self, sock: socket.socket,
                            preread: bytes = b"") -> None:
        """Bootstrap over an already-connected socket: both sides swap Address blobs,
        then open one-sided windows (ref: ``exchange_data`` over the TCP fd,
        ``rdma_bp_posix.cc:640-692``; MR swap ``pair.cc:472-486``).  The socket stays
        alive as the notify/liveness channel.

        The handshake is bounded by ``BOOTSTRAP_TIMEOUT_S``: a peer that connects
        but never speaks (port scanner, platform-mismatched server that handed the
        socket straight to its app) produces a timeout error, not a hang."""
        if self.state is not PairState.INITIALIZED:
            raise RuntimeError(f"connect in state {self.state}")
        sock.settimeout(BOOTSTRAP_TIMEOUT_S)
        try:
            _send_blob(sock, self.local_address().to_bytes())
            peer = Address.from_bytes(_recv_blob(sock, preread))
        except socket.timeout as exc:
            raise ConnectionError(
                f"pair bootstrap timed out after {BOOTSTRAP_TIMEOUT_S}s "
                "(peer not speaking the ring bootstrap protocol?)") from exc
        finally:
            try:
                sock.settimeout(None)
            except OSError:
                pass
        self._attach_peer(peer)
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (e.g. unix socketpair)
        self.notify_sock = sock

    def _attach_peer(self, peer: Address) -> None:
        if peer.domain_kind != self.domain.kind:
            raise ValueError(f"domain mismatch: {peer.domain_kind} vs {self.domain.kind}")
        # Reference asserts ring sizes match (pair.cc:148-149); we allow asymmetric
        # rings — the writer just honors the peer's capacity.
        self._peer_ring = self.domain.open_window(peer.ring_handle, peer.ring_size)
        self._peer_status = self.domain.open_window(peer.status_handle, STATUS_BYTES)
        self._peer_ring_handle = peer.ring_handle
        self.peer_caps = peer.caps
        self.writer = RingWriter(peer.ring_size, self._peer_ring.write,
                                 mapped=self._peer_ring.view)
        self.writer.flight_tag = self._ftag
        self.state = PairState.CONNECTED
        _flight.emit(_flight.PAIR_CONNECT, self._ftag, peer.ring_size)
        trace_ring.log("pair %s connected (peer tag %s, ring %d)",
                       self.tag, peer.tag, peer.ring_size)

    # -- notify channel (completion events) ----------------------------------

    # -- waiter advertisement (futex-style sleep handshake) -------------------

    def _status_pin(self):
        """Cached (array, addr) pin of our status region, or None.

        The array reference is what makes the cached address safe: it holds a
        buffer export, so the region cannot unmap under a native call that
        grabbed the pin into a local (teardown nulls the cache FIRST, then
        Region.close retries its release for the in-flight window)."""
        pin = self._status_np
        if pin is None:
            region = self.status_region
            if region is None:
                return None
            try:
                pin = _native.pin(region.buf, writable=True)
            except (ValueError, TypeError):
                return None  # racing teardown
            self._status_np = pin
            if self.status_region is not region:
                # Teardown nulled the attribute between our read and the
                # cache store; a cached export would wedge Region.close's
                # retry forever. Drop it — our local still pins safely for
                # this one call (the retry covers that bounded window).
                self._status_np = None
                return None
        return pin

    def _peer_status_pin(self):
        pin = self._peer_status_np
        if pin is None:
            win = self._peer_status
            if win is None or win.view is None:
                return None
            try:
                pin = _native.pin(win.view, writable=False)
            except (ValueError, TypeError):
                return None
            self._peer_status_np = pin
            if self._peer_status is not win:  # see _status_pin
                self._peer_status_np = None
                return None
        return pin

    def set_waiting(self, role: str, flag: bool) -> None:
        """Publish 'this role is blocked on the notify fd' in our status
        region, where the peer's data/credit producer reads it (one-sided,
        like everything else in the status page). seq_cst store = the full
        fence the sleep protocol's Dekker argument needs (ring.cc).

        No-op without the native lib: then producers notify unconditionally
        (`_peer_waiting` returns True), which is the pre-advertisement
        behavior — correct, just one syscall heavier per send."""
        lib = _native.load()
        if lib is None:
            return
        pin = self._status_pin()
        if pin is None:
            return  # racing teardown; waiters re-check state and exit
        lib.tpr_store_u64_seqcst(pin[1] + _WAIT_OFF[role], 1 if flag else 0)

    def _peer_waiting(self, role: str) -> bool:
        """Is the peer's ``role`` waiter blocked on its notify fd?  True also
        when we can't tell (no native fences / window gone) — then the caller
        sends the notify byte unconditionally, trading a syscall for safety.

        The fenced load after our data/footer/header stores is the producer
        half of the sleep protocol (StoreLoad ordering; ring.cc).

        Negotiated: only a peer that advertised "waitflag" at bootstrap (its
        process has the native fences and DOES publish the words) may have
        its notifies skipped — an asymmetric peer (TPURPC_NATIVE=0, older
        build) leaves the words at 0 forever, which without the capability
        gate would read as "nobody is waiting" and hang it permanently."""
        lib = _native.load()
        if lib is None or "waitflag" not in self.peer_caps:
            return True
        pin = self._peer_status_pin()
        if pin is None:
            return True
        return bool(lib.tpr_load_u64_fenced(pin[1] + _WAIT_OFF[role]))

    def _notify(self, token: bytes) -> None:
        # cross-process message: the transport seam makes the token's
        # send timing an explorable pick under simnet (the raw socket
        # send stays in _notify_raw — the xproc lint rule's allowance)
        _transport.dispatch("frame", self, self._notify_raw, token)

    def _notify_raw(self, token: bytes) -> None:
        sock = self.notify_sock
        if sock is None:
            return
        try:
            # Always locked since tpurpc-hive: the notify stream now also
            # carries multi-byte re-arm frames (_send_frame), and a token
            # landing INSIDE a frame corrupts the peer's parser. (TLS needed
            # the lock anyway — OpenSSL forbids concurrent use of one SSL*,
            # the TcpEndpoint fix.) Single-byte sends can't partially
            # complete, so a dropped token under EAGAIN stays best-effort.
            with self._notify_lock:
                sock.send(token)
        except (ssl.SSLWantWriteError, ssl.SSLWantReadError):
            pass  # TLS record stalled mid-flight; same as a saturated channel
        except (BlockingIOError, InterruptedError):
            pass  # event channel saturated — busy/hybrid pollers don't need it
        except OSError:
            # Best-effort: a send failure here usually means the peer already
            # left (EPIPE after its graceful close) — the authoritative death
            # signals are the peer_exit status word and the RECV-side probe
            # (empty read) in drain_notifications/peek_events. Marking ERROR
            # here turned every graceful close into a poisoned receive path
            # for whatever data was still draining.
            pass

    def _send_frame(self, payload: bytes, timeout_s: float = 5.0) -> bool:
        """Ship a multi-byte park-protocol frame over the notify stream,
        contiguously (the lock excludes token sends) and completely (the
        socket is non-blocking; a PARTIAL frame would corrupt the peer's
        parser, so retry to a bounded deadline instead of dropping)."""
        return bool(_transport.dispatch("frame", self, self._send_frame_raw,
                                        payload, timeout_s))

    def _send_frame_raw(self, payload: bytes, timeout_s: float = 5.0) -> bool:
        import select as _select

        sock = self.notify_sock
        if sock is None:
            return False
        deadline = time.monotonic() + timeout_s
        sent = 0
        with self._notify_lock:
            while sent < len(payload):
                try:
                    sent += sock.send(payload[sent:])
                except (BlockingIOError, InterruptedError,
                        ssl.SSLWantWriteError, ssl.SSLWantReadError):
                    if time.monotonic() >= deadline:
                        return False
                    try:
                        _select.select([], [sock.fileno()], [], 0.05)
                    except (OSError, ValueError):
                        return False
                except OSError:
                    return False
        return True

    def drain_notifications(self) -> bytes:
        """Non-blocking drain of the peer-event channel; returns the tokens seen.
        An empty-read (peer closed) flips the pair to ERROR, the moral equivalent of
        the reference's TCP-fd zero-byte liveness probe (``rdma_conn.h:90-99``).

        Serialized end to end (``_drain_mu``) since tpurpc-hive: the stream
        now carries park-protocol bytes and framed re-arm blobs whose parse
        requires seeing the bytes in order — two waiters recv'ing
        concurrently would interleave a split frame.  Park-protocol bytes
        are acted on here and stripped; callers see only the classic
        data/credit/exit tokens."""
        with self._drain_mu:
            raw = self._drain_raw()
            if not raw and not self._notify_buf:
                return raw
            if not self._notify_buf and not raw.translate(None,
                                                          _CLASSIC_TOKENS):
                return raw  # fast path: classic tokens only
            return self._fold_park_tokens(raw)

    def _drain_raw(self) -> bytes:
        sock = self.notify_sock
        if sock is None:
            return b""
        is_tls = hasattr(sock, "pending")
        out = b""
        while True:
            try:
                if is_tls:
                    # serialize with _notify's sends (see there: concurrent
                    # SSL_read/SSL_write on one SSL* is UB). recv is
                    # non-blocking — the lock hold is microseconds.
                    with self._notify_lock:
                        chunk = sock.recv(65536)
                else:
                    chunk = sock.recv(65536)
            except (BlockingIOError, InterruptedError,
                    ssl.SSLWantReadError, ssl.SSLWantWriteError):
                break  # nothing decryptable yet ≡ EAGAIN on a plain socket
            except OSError:
                # A STALE caller may hold a socket from a previous life of
                # this pooled pair (teardown closed it; init() replaced it):
                # its EBADF must not poison the pair's NEW connection.
                if sock is self.notify_sock and sock.fileno() != -1:
                    self._mark_error("notify channel read failed")
                break
            if chunk == b"":
                if sock is self.notify_sock:  # stale-life guard (see peek)
                    self._on_notify_closed()
                break
            out += chunk
            if len(chunk) < 65536:
                break  # drained; skip the guaranteed-EAGAIN second recv
        return out

    # -- idle-pair parking (tpurpc-hive, ISSUE 16) ----------------------------

    def _fold_park_tokens(self, raw: bytes) -> bytes:
        """Act on and strip park-protocol bytes; return the classic tokens.
        Caller holds ``_drain_mu`` (stream order).  A re-arm frame split
        across recv chunks is stashed in ``_notify_buf`` until complete —
        the sender shipped it atomically, so the rest is already in flight."""
        data = self._notify_buf + raw
        self._notify_buf = b""
        out = bytearray()
        i = 0
        n = len(data)
        while i < n:
            tok = data[i:i + 1]
            if tok == NOTIFY_PARK:
                i += 1
                self._handle_park_request()
            elif tok == NOTIFY_PARK_ACK:
                i += 1
                self._complete_park()
            elif tok == NOTIFY_PARK_NACK:
                i += 1
                with self._park_lock:
                    self._park_pending = False
            elif tok == NOTIFY_WAKE:
                i += 1
                self._handle_wake_request()
            elif tok in (NOTIFY_REARM, NOTIFY_REARM_KEEP):
                frame = data[i + 1:]
                if len(frame) < 8:
                    self._notify_buf = data[i:]
                    break
                if frame[:4] != _BOOTSTRAP_MAGIC:
                    self._mark_error("corrupt re-arm frame on notify stream")
                    break
                blen = struct.unpack("<I", frame[4:8])[0]
                if blen > _MAX_BLOB:
                    self._mark_error("re-arm frame implausibly large")
                    break
                if len(frame) < 8 + blen:
                    self._notify_buf = data[i:]
                    break
                self._handle_rearm(frame[8:8 + blen],
                                   retained=(tok == NOTIFY_REARM_KEEP))
                i += 1 + 8 + blen
            else:
                out += tok
                i += 1
        return bytes(out)

    def _handle_park_request(self) -> None:
        """Peer announced it will park: close our one-sided windows into its
        regions (after this no stale write of ours can land there — the pool
        invariant), snapshot the writer position for an abort-restore, ack."""
        with self._park_lock:
            if self.state is not PairState.CONNECTED or self.want_write:
                self._notify(NOTIFY_PARK_NACK)
                return
            try:
                # excludes an in-flight send; a send ENTERING after us gets
                # the retryable _ParkBusy, not the caller-bug tripwire
                with self._send_guard.maintenance():
                    if self.want_write:
                        self._notify(NOTIFY_PARK_NACK)
                        return
                    w = self.writer
                    if w is not None:
                        self._saved_wstate = (self._peer_ring_handle, w.tail,
                                              w.seq, w.remote_head)
                    self.writer = None
                    for attr in ("_peer_ring", "_peer_status"):
                        win = getattr(self, attr)
                        if win is not None:
                            setattr(self, attr, None)
                            self._peer_status_np = None
                            retry_buffer_op(win.close)
                    self._peer_parked = True
            except AssertionError:
                # a sender is inside send() right now — the pair is not idle
                self._notify(NOTIFY_PARK_NACK)
                return
        self._notify(NOTIFY_PARK_ACK)

    def _complete_park(self) -> None:
        """Peer acked our park request: its windows into our regions are
        closed, so they are writer-free — the one condition under which they
        may enter the shared :class:`RingPool`.  Re-check the ring FIRST:
        bytes that landed between our park decision and the peer's window
        close (the park-decide vs incoming-byte race) abort the park."""
        released = 0
        aborted = False
        with self._park_lock:
            if not self._park_pending:
                return
            self._park_pending = False
            if self.state is not PairState.CONNECTED:
                return
            try:
                # _recv_guard RAISES on concurrent entry: a receiver mid-
                # drain means the pair is not idle — abort, don't block.
                # maintenance entry: a receiver racing US retries as empty
                with self._recv_guard.maintenance():
                    if self.readable() or self.has_message():
                        aborted = True
                    else:
                        # The wake pipes and waiter selectors SURVIVE the
                        # park: a waiter asleep on them stays reachable by
                        # kick() across the whole episode, so unpark can
                        # never lose its wakeup. Only the rings (the actual
                        # memory) and the reader go; ~fd-sized stub remains.
                        pool = RingPool.get()
                        if self.reader is not None:
                            self.reader.release()
                            self.reader = None
                        self._status_np = None
                        for attr in ("recv_region", "status_region"):
                            region = getattr(self, attr)
                            if region is not None:
                                setattr(self, attr, None)
                                try:
                                    released += len(region.buf)
                                except ValueError:
                                    pass
                                pool.release(region)
                        self._published_head_mirror = 0
                        self._parked = True
                        self.parked_epochs += 1
            except AssertionError:
                aborted = True
        if aborted:
            # our rings survive untouched — re-arm the peer's write side
            # against the SAME handles (its saved writer state restores)
            self._send_rearm(retained=True)
            self.kick()
            return
        _flight.emit(_flight.PAIR_PARK, self._ftag, released)
        _stats.counter_inc("pair_park")
        from tpurpc.core.poller import Poller

        Poller.note_parked(self)
        trace_ring.log("pair %s parked (%d ring bytes pooled)",
                       self.tag, released)

    def unpark(self, *, remote: bool = False) -> None:
        """Re-arm a parked pair: lease fresh rings from the pool, rebuild the
        receive plumbing, and ship the new Address to the peer.  Invisible to
        the RPC layers — callers' sends/recvs resume on the fresh rings."""
        leased = 0
        with self._park_lock:
            if not self._parked:
                return
            if self.state is not PairState.CONNECTED:
                return  # dying while parked; teardown forgets the stub
            pool = RingPool.get()
            ring = pool.lease(self.domain, self.ring_size)
            try:
                status = pool.lease(self.domain, STATUS_BYTES)
            except BaseException:
                pool.release(ring)
                raise
            try:
                self.recv_region = ring
                self.status_region = status
                self.recv_region.on_write = self.kick
                self.status_region.on_write = self.kick
                self.reader = RingReader(self.recv_region.buf, self.ring_size)
                self._published_head_mirror = 0
                self._parked = False
            except BaseException:
                # lease-pairing discipline (lint rule `ringpool`): a failed
                # re-arm returns both rings to the pool
                self.recv_region = None
                self.status_region = None
                self.reader = None
                pool.release(ring)
                pool.release(status)
                raise
            leased = self.ring_size + STATUS_BYTES
            self._send_rearm()
        _flight.emit(_flight.PAIR_UNPARK, self._ftag, leased,
                     1 if remote else 0)
        _stats.counter_inc("pair_unpark")
        from tpurpc.core.poller import Poller

        Poller.note_unparked(self)
        self.kick()
        trace_ring.log("pair %s unparked (%s)", self.tag,
                       "remote wake" if remote else "local demand")

    def _send_rearm(self, *, retained: bool = False) -> None:
        """Frame our current Address over the notify stream — the peer
        reopens windows onto these rings and rebuilds its writer."""
        if (self.recv_region is None or self.status_region is None
                or self.state not in (PairState.INITIALIZED,
                                      PairState.CONNECTED)):
            return
        blob = self.local_address().to_bytes()
        tok = NOTIFY_REARM_KEEP if retained else NOTIFY_REARM
        frame = tok + _BOOTSTRAP_MAGIC + struct.pack("<I", len(blob)) + blob
        if not self._send_frame(frame):
            self._mark_error("re-arm frame could not be delivered")

    def _handle_wake_request(self) -> None:
        """Peer has bytes for us but believes our rings are parked — re-arm.
        When we are NOT parked (the WAKE crossed our re-arm in flight, or an
        ack-overdue sender gave up on a park the peer did honor), re-send the
        current Address: the peer's duplicate-re-arm dedup makes this
        idempotent, and it repairs a peer stuck with its windows closed."""
        if self._parked:
            try:
                self.unpark(remote=True)
            except Exception as exc:  # pool exhaustion / racing teardown
                trace_ring.log("pair %s: remote unpark failed: %r",
                               self.tag, exc)
        elif self.state is PairState.CONNECTED:
            self._send_rearm(retained=True)
            self.kick()

    def _handle_rearm(self, blob: bytes, *, retained: bool = False) -> None:
        """Peer advertised (fresh or retained) rings: rebuild our write side.
        Duplicate re-arms for rings we already write are ignored — rebuilding
        a live writer would reset its position mid-stream."""
        try:
            peer = Address.from_bytes(blob)
        except Exception:
            self._mark_error("undecodable re-arm frame")
            return
        with self._park_lock:
            saved, self._saved_wstate = self._saved_wstate, None
            if self.writer is not None:
                if self._peer_ring_handle == peer.ring_handle:
                    return  # duplicate
                # stale windows onto rings the peer replaced: close first
                try:
                    with self._send_guard.maintenance():
                        self.writer = None
                        for attr in ("_peer_ring", "_peer_status"):
                            win = getattr(self, attr)
                            if win is not None:
                                setattr(self, attr, None)
                                self._peer_status_np = None
                                retry_buffer_op(win.close)
                except AssertionError:
                    self._mark_error("re-arm raced an in-flight send")
                    return
            try:
                self._peer_ring = self.domain.open_window(peer.ring_handle,
                                                          peer.ring_size)
                self._peer_status = self.domain.open_window(peer.status_handle,
                                                            STATUS_BYTES)
            except Exception as exc:
                self._mark_error(f"re-arm window open failed: {exc!r}")
                return
            self._peer_ring_handle = peer.ring_handle
            self.writer = RingWriter(peer.ring_size, self._peer_ring.write,
                                     mapped=self._peer_ring.view)
            self.writer.flight_tag = self._ftag
            if retained:
                # park ABORT / repair: the peer kept its rings and its reader
                # position — restore our exact write position (a fresh
                # writer's zero tail would corrupt mid-ring)
                if saved is not None and saved[0] == peer.ring_handle:
                    _, self.writer.tail, self.writer.seq, rh = saved
                    self.writer.remote_head = rh
                else:
                    # rings retained but our snapshot is gone/mismatched: any
                    # guess at the write position corrupts the stream — fail
                    # loudly instead (never observed; belt and braces)
                    self._mark_error("retained re-arm without writer state")
                    return
            elif self.status_region is not None:
                # fresh peer rings: its reader restarts at head 0, so the
                # stale published-head word in OUR status region must never
                # fold into the fresh writer. The peer cannot be publishing
                # concurrently — it publishes only after reading data, and
                # no data can flow until this writer exists.
                try:
                    self.status_region.buf[
                        _STATUS_HEAD_OFF:_STATUS_HEAD_OFF + 8] = bytes(8)
                except (ValueError, TypeError):
                    pass  # racing teardown; state checks surface it
            self._peer_parked = False
        if self.want_write:
            self.process_credits()
        self.kick()

    def maybe_park(self, now: float, park_s: float) -> bool:
        """Poller-sweep hook: initiate (or progress) a park episode for an
        idle pair.  Returns True when park budget was consumed."""
        if (self._parked or self.notify_sock is None
                or "park" not in self.peer_caps):
            return False
        if self._park_pending:
            if now - self._park_sent_at > 2.0:
                with self._park_lock:
                    self._park_pending = False  # ack lost/peer gone; retry
            # an ownerless idle pair has no waiter to consume the ack —
            # drain here (kick after: token theft is safe only with a kick)
            if self.drain_notifications():
                self.kick()
            return False
        if (self.state is not PairState.CONNECTED or self.want_write
                or self.has_message() or self.readable()
                or now - self.last_activity < park_s):
            return False
        with self._park_lock:
            if self._park_pending or self._parked:
                return False
            try:
                with self._send_guard.maintenance():
                    if self.want_write or self.has_message():
                        return False
                    # the flag is visible to any sender that enters the
                    # guard after us — no write can race the peer's
                    # window-close (senders divert to the park-aware path)
                    self._park_pending = True
                    self._park_sent_at = now
            except AssertionError:
                return False  # a sender is mid-flight: not idle
        self._notify(NOTIFY_PARK)
        _stats.counter_inc("pair_park_requested")
        return True

    def resident_bytes_est(self) -> int:
        """Estimated per-connection resident bytes this pair pins: ring
        allocations while live, a ~stub while parked (scrape-time gauge and
        the hive bench's bytes/connection curve)."""
        n = 256  # object + bookkeeping stub
        region = self.recv_region
        if region is not None:
            n += self.ring_size
        if self.status_region is not None:
            n += STATUS_BYTES
        return n

    def _on_notify_closed(self) -> None:
        """Peer's end of the notify socket closed. Graceful close writes
        peer_exit BEFORE closing (``Disconnect`` pair.cc:325-347), so fold the
        status words first; only an unexplained closure is an ERROR (the
        crash-detection analog of the zero-byte TCP probe, rdma_conn.h:90-99).

        ASYNC domains (tcp_window) add a wrinkle: the exit word travels the
        record stream while the EOF travels the notify socket — the EOF can
        win the race even on a graceful close. Give the exit word a short
        grace window before declaring the peer crashed (the record stream
        delivers in milliseconds when the peer is alive enough to have
        closed gracefully; a genuinely crashed peer never sets it and we
        error after the window exactly as before)."""
        if self.state is PairState.CONNECTED:
            self.process_credits()  # may observe peer_exit -> HALF_CLOSED
        if self.state is PairState.CONNECTED and self.domain.kind not in (
                "local", "shm"):
            deadline = time.monotonic() + 2.0
            while (self.state is PairState.CONNECTED
                   and time.monotonic() < deadline):
                time.sleep(0.005)
                self.process_credits()
        if self.state is PairState.CONNECTED:
            self._mark_error("peer vanished (notify socket closed)")

    def peek_events(self) -> bool:
        """Non-consuming probe of the notify channel (``MSG_PEEK``): True if events
        are pending or the peer died.  The background :class:`~tpurpc.core.poller.
        Poller` uses this so it never steals tokens an event-discipline waiter is
        blocked on — only the pair's owner consumes via
        :meth:`drain_notifications`."""
        sock = self.notify_sock
        if sock is None:
            return False
        if hasattr(sock, "pending"):
            # SSLSocket: MSG_PEEK is unsupported (ValueError on flags) and
            # meaningless on a record stream. A non-consuming HINT suffices
            # for the poller's purpose: decrypted bytes pending, or raw
            # ciphertext readable on the fd (a spurious True just makes the
            # owner drain and find nothing). pending() reads SSL state —
            # serialized with sends/recvs like every other SSL op.
            with self._notify_lock:
                if sock.pending():
                    return True
            import select

            try:
                r, _, _ = select.select([sock.fileno()], [], [], 0)
            except (OSError, ValueError):
                return True  # racing close; owner's drain will resolve it
            return bool(r)
        try:
            chunk = sock.recv(1, socket.MSG_PEEK)
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            # Poller scans race pool recycling: a captured socket from the
            # pair's PREVIOUS life (closed at quiesce, replaced by init)
            # raises EBADF here — benign staleness, not a liveness failure;
            # marking would poison whatever connection holds the pair NOW.
            if sock is self.notify_sock and sock.fileno() != -1:
                self._mark_error("notify channel read failed")
                return True
            return False
        if chunk == b"":
            if sock is self.notify_sock:
                self._on_notify_closed()
            return True
        return True

    # -- wakeup fds (local poller -> blocked selector) ------------------------

    @property
    def wakeup_fd(self) -> int:
        """The read-waiter wakeup fd (``grpc_endpoint_get_fd`` analog)."""
        return self._wake_r["read"]

    def wakeup_fd_for(self, role: str) -> int:
        return self._wake_r[role]

    def kick(self, exclude: Optional[str] = None) -> None:
        """Wake every blocked waiter on this pair (``poller.cc:92-101`` writing
        the pair's ``grpc_wakeup_fd``).

        Unconditional non-blocking writes: round 1 guarded this with an
        "armed" flag cleared by the consumer, and the window between a
        consumer draining the byte and clearing the flag suppressed
        concurrent kicks — a lost wakeup the old 50 ms select cap papered
        over. A redundant byte in a pipe is free; a suppressed kick is a
        stall. EAGAIN on a full pipe means a byte is already pending, which
        is exactly the required post-condition.

        ``exclude`` skips one role's pipe: a waiter that just drained shared
        notify tokens re-checks its own predicate immediately, so kicking
        itself only buys a guaranteed spurious wake (an extra select+consume
        round per RPC, measured on the 64B path)."""
        for role in ("read", "write"):
            if role == exclude:
                continue
            fd = self._wake_w[role]
            if fd >= 0:
                try:
                    os.write(fd, b"\x01")
                except (BlockingIOError, OSError):
                    pass

    def consume_wakeup(self, role: str = "read") -> None:
        fd = self._wake_r[role]
        if fd < 0:
            return
        try:
            while os.read(fd, 64):
                pass
        except (BlockingIOError, OSError):
            pass

    def waiter_selector(self, role: str):
        """The role's persistent selector over (notify socket, role pipe);
        created lazily, lives until the connection's channels are released.
        Only the role's single waiter thread touches it (ContentAssertion
        enforces one reader + one writer)."""
        import selectors

        sel = self._selectors.get(role)
        if sel is None:
            sel = selectors.DefaultSelector()
            try:
                if self.notify_sock is not None:
                    sel.register(self.notify_sock, selectors.EVENT_READ)
                fd = self._wake_r[role]
                if fd >= 0:
                    sel.register(fd, selectors.EVENT_READ)
            except (OSError, ValueError, KeyError):
                pass  # racing close; the waiter's predicate re-check handles it
            self._selectors[role] = sel
        return sel

    # -- status / credits -----------------------------------------------------

    def _poll_status_words(self) -> Tuple[int, int]:
        buf = self.status_region.buf
        return (_U64.unpack_from(buf, _STATUS_HEAD_OFF)[0],
                _U64.unpack_from(buf, _STATUS_EXIT_OFF)[0])

    def process_credits(self) -> None:
        """Fold the peer-written status buffer into local writer state
        (``pair.cc:294-301`` reading mirrored remote_head; peer_exit check
        ``pair.cc:349-375``).  Serialized: sender thread and poller thread both call
        this, and check-then-act on ``remote_head`` must be atomic."""
        w = self.writer
        if w is None or self.status_region is None:
            return  # no write side / our status inbox is parked in the pool
        with self._credit_lock:
            try:
                head, peer_exit = self._poll_status_words()
            except ValueError:
                return  # region released under us (park/teardown race)
            if head > w.remote_head:
                w.update_remote_head(head)
        if peer_exit and self.state is PairState.CONNECTED:
            self.state = PairState.HALF_CLOSED
            trace_ring.log("pair %s: peer_exit observed -> HALF_CLOSED", self.tag)

    def _publish_credits_if_due(self, force: bool = False) -> None:
        """One-sided-write our head into the peer's status buffer after consuming
        ≥ half ring (``pair.cc:276-284``, ``updateStatus`` ``:624-641``)."""
        reader = self.reader
        win = self._peer_status
        if win is None or reader is None:
            return  # reader parked: head 0 re-publishes on the fresh ring
        if force or reader.should_publish_head():
            head = reader.take_publish()
            if head != self._published_head_mirror:
                self._published_head_mirror = head
                try:
                    win.write(_STATUS_HEAD_OFF, _U64.pack(head))
                except ValueError:
                    # window closed under us (peer parking): the publish is
                    # lost but heads are cumulative — the next publish after
                    # re-arm carries it
                    return
                # Wake the peer's credit-stalled writer only if one is
                # actually asleep; a spinning writer watches the head word
                # natively (tpr_spin_u64_change) and needs no byte.
                if force or self._peer_waiting("write"):
                    self._notify(NOTIFY_CREDIT)

    # -- data plane -----------------------------------------------------------

    def send(self, slices: Sequence, byte_idx: int = 0) -> int:
        """Send as much of ``slices[byte_idx:]`` as flow control allows; returns bytes
        accepted.  Partial sends are normal — the caller re-arms on write-ready
        (``rdma_flush`` loop + ``notify_on_write``, ``rdma_bp_posix.cc:470-586``).
        Large payloads are chunked to ``send_chunk_size`` per ring message
        (old-gen chunked flush, ``rdma_utils.h:87-92``)."""
        # HALF_CLOSED is not sendable either: the peer has left and will never drain
        # its ring or return credits — accepting bytes would black-hole them.
        if self.state is not PairState.CONNECTED:
            raise BrokenPipeError(f"pair {self.tag} not sendable: {self.state}"
                                  + (f" ({self.error})" if self.error else ""))
        t0 = time.monotonic_ns()
        while True:
            try:
                if _tracing.LIVE and _tracing.current() is not None:
                    # traced call on this thread: the ring-encode interval is
                    # the "send-lease" span of the timeline (SURVEY §7 #4)
                    with _tracing.span("send-lease"):
                        n = self._send_traced(slices, byte_idx)
                else:
                    n = self._send_traced(slices, byte_idx)
                break
            except _ParkBusy:
                n = self._resolve_park_for_send()
                if n is not None:
                    break  # peer parked: 0 accepted, wake in flight
        # tpurpc-lens `wire` hop: bytes accepted across the transport
        # boundary and the nanoseconds the placement (credits + chunking +
        # ring encode) took — one pair of bumps per send call
        dt = time.monotonic_ns() - t0
        _LENS_WIRE_NS.inc(dt)
        _LENS_WIRE_BYTES.inc(n)
        _LENS_WIRE_COPY.inc(n)
        return n

    def _resolve_park_for_send(self) -> Optional[int]:
        """Resolve the park episode that made ``_send_inner`` raise
        :class:`_ParkBusy` — called OUTSIDE the send guard (lock order).
        Returns a byte count for ``send`` to report (peer parked: 0 accepted,
        partial-send semantics — the endpoint re-arms on write-ready and the
        WAKE token is already in flight), or None to retry the send."""
        with self._park_lock:
            peer_parked = self._peer_parked
            parked = self._parked
            pending = self._park_pending
        if peer_parked:
            # each retry re-sends the wake: idempotent, and it makes a lost
            # token survivable (the endpoint's wait_writable has a timeout)
            self._notify(NOTIFY_WAKE)
            self.want_write = True
            return 0
        if parked:
            self.unpark()
            return None
        if pending:
            # our own park request is in flight; drain for the ack/nack so
            # the episode resolves, bounded so a dead peer can't wedge senders
            deadline = time.monotonic() + 2.5
            while time.monotonic() < deadline:
                if self.drain_notifications():
                    self.kick()  # stolen tokens: waiters re-check predicates
                with self._park_lock:
                    if not (self._park_pending or self._parked
                            or self._peer_parked):
                        return None
                    if self._parked or self._peer_parked:
                        return None  # resolved; next retry takes that branch
                if self.state is not PairState.CONNECTED:
                    return None  # retry surfaces the state error
                time.sleep(0.001)
            with self._park_lock:
                self._park_pending = False  # ack overdue; peer likely gone
        return None

    def _send_traced(self, slices: Sequence, byte_idx: int = 0) -> int:
        if _stats.profiling_on():
            with _stats.profile("pair_send"):
                return self._send_profiled(slices, byte_idx)
        return self._send_profiled(slices, byte_idx)

    def _send_profiled(self, slices: Sequence, byte_idx: int = 0) -> int:
        # tpurpc-blackbox: emit want_write EDGES only (stall begin/end) —
        # the bool compare in the finally is the whole per-send cost
        was_stalled = self.want_write
        try:
            return self._send_inner(slices, byte_idx)
        finally:
            now_stalled = self.want_write
            if now_stalled != was_stalled:
                if now_stalled:
                    _flight.emit(_flight.WRITE_STALL_BEGIN, self._ftag)
                    # distinguish "partial send re-armed" from "writer is
                    # OUT of credits" — every fast/slow path that stalls
                    # with zero writable payload is a starvation edge
                    w = self.writer
                    if (w is not None and not self._starve_open
                            and w.writable_payload() == 0):
                        self._starve_open = True
                        inflight = w.tail - w.remote_head
                        _flight.emit(_flight.CREDIT_STARVE_BEGIN,
                                     self._ftag, inflight)
                else:
                    _flight.emit(_flight.WRITE_STALL_END, self._ftag)
                    if self._starve_open:
                        self._starve_open = False
                        _flight.emit(_flight.CREDIT_STARVE_END, self._ftag)

    def _send_inner(self, slices: Sequence, byte_idx: int = 0) -> int:
        cfg = get_config()
        with self._send_guard:
            if self._parked or self._park_pending or self._peer_parked:
                # checked INSIDE the guard: park initiation/ack also hold it,
                # so a sender entering after a park decision always observes
                # the flag — no write can race the peer's window close
                raise _ParkBusy
            views: List[memoryview] = []
            skip = byte_idx
            for s in slices:
                v = memoryview(s).cast("B")
                if skip >= len(v):
                    skip -= len(v)
                    continue
                views.append(v[skip:] if skip else v)
                skip = 0
            fast = self._send_fast(views, cfg)
            if fast is not None:
                return fast
            self.process_credits()
            total = 0
            while views:
                # Batch EVERY chunk the current credits admit into one
                # writer.write_many call (one bulk ring placement + one
                # header store per chunk) instead of a writev per chunk —
                # the gather-side half of the batched pipeline. Chunks stay
                # ≤ send_chunk_size so the peer's drain granularity (and
                # the old-gen chunked-flush semantics) are unchanged.
                budget = self.writer.writable_payload()
                if budget == 0:
                    self.want_write = True
                    if not self._starve_open:
                        self._starve_open = True
                        _flight.emit(_flight.CREDIT_STARVE_BEGIN, self._ftag,
                                     self.writer.tail
                                     - self.writer.remote_head)
                    break
                chunks: List[List[memoryview]] = []
                n = 0
                while views and n < budget:
                    chunk: List[memoryview] = []
                    c = 0
                    room = min(cfg.send_chunk_size, budget - n)
                    while views and c < room:
                        v = views[0]
                        take = min(len(v), room - c)
                        chunk.append(v[:take])
                        if take == len(v):
                            views.pop(0)
                        else:
                            views[0] = v[take:]
                        c += take
                    chunks.append(chunk)
                    n += c
                    # every chunk's framing overhead eats writable payload;
                    # leave the precise accept/stop decision to write_many
                    budget = max(0, budget - (c + 24))
                wrote_msgs, wrote_bytes = self.writer.write_many(chunks)
                if wrote_msgs:
                    _stats.batch_hist("ring_write").record(wrote_msgs)
                if wrote_msgs < len(chunks):
                    # credits moved under us: re-queue the unwritten chunks'
                    # segments (identity-preserving) and stall for credits
                    views[0:0] = [seg for ch in chunks[wrote_msgs:]
                                  for seg in ch]
                    total += wrote_bytes
                    self.want_write = True
                    break
                total += wrote_bytes
            if not views:
                self.want_write = False
            self.total_sent += total
            # ONE completion event per send call, not per chunk (round 1's
            # per-chunk token was a measured throughput killer) — and only
            # when a receiver is actually ASLEEP on its notify fd. A spinning
            # receiver sees the ring header the instant it lands; skipping
            # the byte makes the BP/BPEV fast path a zero-syscall send, the
            # reference's defining property (its RDMA WRITE needs no
            # completion on the passive side; only the event path wakes via
            # the completion channel, poller.cc:92-101). The waiting flag +
            # fences make the skip lossless (ring.cc sleep-protocol proof).
            if total:
                self.last_activity = time.monotonic()
                if self._peer_waiting("read"):
                    self._notify(NOTIFY_DATA)
            return total

    def _send_fast(self, views: "List[memoryview]", cfg) -> "Optional[int]":
        """Fused native send (``tpr_send_fast``): credit fold + chunked
        gather-encode + the sleep-protocol notify decision collapse into one
        GIL-held C call — the ~10 Python-level steps of the slow path are
        the measured per-RPC overhead in the multi-core spin regime.
        Returns bytes accepted, or None when the fast path doesn't apply
        (no native lib, unmapped ring, teardown racing)."""
        lib = _native.load()
        writer = self.writer
        if (lib is None or writer is None or writer._nat is None
                or not views):
            return None
        status_pin = self._status_pin()
        if status_pin is None:
            return None
        peer_rxwait = 0
        if "waitflag" in self.peer_caps:
            peer_pin = self._peer_status_pin()
            if peer_pin is not None:
                peer_rxwait = peer_pin[1] + _STATUS_RXWAIT_OFF
        # Small gather lists coalesce into ONE buffer first: address
        # extraction costs a numpy construction per segment (~1µs), which
        # exceeds the memcpy of a few hundred bytes — one staging copy + one
        # pin beats N pins on the small-RPC path. Large payloads keep true
        # scatter-gather. (Preallocated fill, not b"".join: the hot-path
        # no-copy lint bans the join idiom outright.)
        small_total = sum(len(v) for v in views)
        if len(views) > 1 and small_total <= 4096:
            staged = bytearray(small_total)
            pos = 0
            for v in views:
                staged[pos:pos + len(v)] = v
                pos += len(v)
            views = [memoryview(staged)]
        n = len(views)
        # locals pin every view for the call's duration
        seg_ptrs = (ctypes.c_void_p * n)(
            *[_native.addr_of(v, writable=False) for v in views])
        seg_lens = (ctypes.c_uint64 * n)(*[len(v) for v in views])
        tail = ctypes.c_uint64(writer.tail)
        seq = ctypes.c_uint64(writer.seq)
        rh = ctypes.c_uint64(writer.remote_head)
        notify = ctypes.c_int(0)
        # The credit lock spans the CALL and the writeback: the peer can
        # consume freshly written bytes and publish a head beyond our stale
        # writer.tail the instant the C call's stores land, and a concurrent
        # process_credits() folding that head against the not-yet-written-
        # back tail would raise a spurious RingCorruption. The call is
        # GIL-held and bounded, so the hold is short.
        seq_before = writer.seq
        t0 = time.monotonic_ns()
        with self._credit_lock:
            got = lib.tpr_send_fast(
                writer._nat_addr, writer.layout.capacity,
                ctypes.byref(tail), ctypes.byref(seq),
                status_pin[1] + _STATUS_HEAD_OFF, ctypes.byref(rh),
                peer_rxwait or None, seg_ptrs, seg_lens, n,
                cfg.send_chunk_size, ctypes.byref(notify))
            writer.tail = tail.value
            writer.seq = seq.value
            if rh.value > writer.remote_head:
                writer.remote_head = rh.value
        dt = time.monotonic_ns() - t0
        if writer.seq > seq_before:  # ring messages this one C call encoded
            _stats.batch_hist("ring_write").record(writer.seq - seq_before)
            # the fused C path bypasses RingWriter.writev, so the registry
            # totals are bumped here (same counters, same meaning) — and so
            # are the lens send_ring hop counters
            _MSGS_OUT.inc(writer.seq - seq_before)
            _BYTES_OUT.inc(got)
            _LENS_SR_BYTES.inc(got)
            _LENS_SR_NS.inc(dt)
            _LENS_SR_COPY.inc(got)
        ring_ledger.host_copy(got)
        self.total_sent += got
        if got:
            self.last_activity = time.monotonic()
        total_len = sum(len(v) for v in views)
        self.want_write = got < total_len
        # the fast path folds only the credit word; peer_exit still must
        # flip state (cheap single unpack — Disconnect, pair.cc:325-347)
        if self.state is PairState.CONNECTED and self.status_region is not None:
            try:
                if _U64.unpack_from(self.status_region.buf,
                                    _STATUS_EXIT_OFF)[0]:
                    self.state = PairState.HALF_CLOSED
            except ValueError:
                pass  # racing teardown; caller's state checks surface it
        if notify.value:
            self._notify(NOTIFY_DATA)
        return got

    def recv_into(self, dst) -> int:
        """Drain the receive ring into ``dst``; publishes credits as a side effect
        (``PairPollable::Recv`` → ``RingBufferPollable::Read``,
        ``ring_buffer.cc:122-191``).

        Rides the BATCHED drain (``RingReader.drain_into``): every complete
        message queued in the ring moves in one pass with one head publish,
        and the batch size feeds the ``ring_drain`` histogram the bench
        reports as ``batch_msgs_per_wakeup``."""
        try:
            return self._recv_into_guarded(dst)
        except _ParkBusy:
            # park completion owns the read side this instant; it either
            # aborts (rings intact, kick re-wakes us) or parks (recv on a
            # parked pair reads 0 anyway) — transient empty, not an error
            return 0

    def _recv_into_guarded(self, dst) -> int:
        with self._recv_guard:
            reader = self.reader
            if reader is None:  # quiesced/destroyed under a racing reader thread
                if self._parked:
                    return 0  # parked, not closed: the first peer byte
                    # arrives as a WAKE on the notify fd and re-arms us —
                    # callers just keep wait_readable-ing, RPC-invisible
                raise ConnectionError("pair is closed")
            try:
                n, nmsgs = reader.drain_into(dst)
            except (RingCorruption, ValueError) as exc:
                # ring memory released by a concurrent teardown — surface as a
                # connection error, not data corruption
                if "released" in str(exc):
                    raise ConnectionError("pair is closed") from None
                raise
            if nmsgs:
                _stats.batch_hist("ring_drain").record(nmsgs)
            self.total_recv += n
            if n:
                self.last_activity = time.monotonic()
            self._publish_credits_if_due()
            return n

    def recv(self, max_bytes: int = 1 << 20) -> bytes:
        cap = self.reader.layout.capacity if self.reader is not None else 0
        buf = bytearray(min(max_bytes, cap))
        n = self.recv_into(buf)
        ring_truncate(buf, n)  # in place: bytes(buf[:n]) would copy twice
        return bytes(buf)

    def has_message(self) -> bool:
        return self.reader is not None and self.reader.has_message()

    def readable(self) -> int:
        return self.reader.readable() if self.reader is not None else 0

    def has_pending_writes(self) -> bool:
        """True when a sender stalled for credits and space has since appeared — the
        poller uses this to wake writers (``poller.cc:77-88`` checking
        ``HasPendingWrites``)."""
        if not self.want_write or self.writer is None:
            return False
        self.process_credits()
        return self.writer.writable_payload() > 0

    # -- native busy-poll (GIL-free) -------------------------------------------

    def spin(self, role: str, timeout_us: int) -> bool:
        """Bounded native spin on the role's watched words, GIL released.

        ``read`` watches the local receive ring for a complete message
        (header+footer words, like ``pollable_epoll``'s ``HasMessage`` scan,
        ``ev_epollex_rdma_bp_linux.cc:1020-1110``); ``write`` watches the
        status buffer's remote-head word the peer one-sided-writes credits
        into (``pair.cc:294-301``). Returns True when the watched condition
        fired OR the spin is impossible (no native lib, memory released) —
        the caller always re-checks the full predicate in Python either way;
        False means the slice timed out quietly.

        The buffer is pinned by an exported view for the call's duration;
        Region.close retries its unmap until spinners unpin (≤ one slice).
        """
        spin = _native.load_spin()
        if spin is None:
            # Pure-Python fallback: no bounded native spin exists, so the
            # caller's loop would become a GIL-held hot poll. Yield the core
            # each lap (the round-1 polling_yield behavior) and let the
            # caller's ready() do the checking.
            time.sleep(0)
            return True
        if role == "read":
            reader = self.reader
            if reader is None or reader._msg_len:
                return True
            pin = reader._nat_pin  # local ref pins the ring across the call
            if pin is None:
                try:
                    pin, addr = _native.pin(reader.buf, writable=True)
                except (ValueError, TypeError):
                    return True  # ring released; predicate will surface it
            else:
                addr = reader._nat_addr
            r = spin.tpr_ring_wait_message(
                addr, reader.layout.capacity, reader.head,
                reader.seq, timeout_us)
            return r != 0
        writer = self.writer
        if writer is None:
            return True
        pin = self._status_pin()  # local ref pins across the GIL-free call
        if pin is None:
            return True
        # Watch for divergence from the last FOLDED credit value, not from the
        # word's current value: a credit that landed between the caller's
        # predicate check and this call returns immediately instead of
        # spinning a whole slice past it.
        r = spin.tpr_spin_u64_change(
            pin[1] + _STATUS_HEAD_OFF, writer.remote_head, timeout_us)
        return r != 0

    # -- close / liveness ------------------------------------------------------

    def get_status(self) -> PairState:
        """Cheap liveness probe: fold in peer_exit + notify-channel health
        (``get_status`` ``pair.cc:349-375``)."""
        if self.state is PairState.CONNECTED:
            self.process_credits()
        return self.state

    def disconnect(self) -> None:
        """Graceful close: one-sided-write ``peer_exit=1`` into the peer's status
        buffer, notify, then stop sending (``Disconnect`` ``pair.cc:325-347``)."""
        if self.state in (PairState.CONNECTED, PairState.HALF_CLOSED):
            self._publish_credits_if_due(force=True)
            try:
                self._peer_status.write(_STATUS_EXIT_OFF, _U64.pack(1))
                self._notify(NOTIFY_EXIT)
            except Exception:
                pass
            _flight.emit(_flight.PAIR_DISCONNECT, self._ftag)
        self.state = PairState.DISCONNECTED
        if self.want_write:
            # balance the open stall edge: a dead pair's stall is over (the
            # sender fails, the RPC surfaces an error) — an unclosed begin
            # would keep the watchdog attributing to credit-starvation for
            # the whole flight-evidence window after the peer is gone
            _flight.emit(_flight.WRITE_STALL_END, self._ftag)
        self.want_write = False  # no sender can stall on a closed pair

    def _mark_error(self, why: str) -> None:
        if self.state not in (PairState.DISCONNECTED,):
            self.state = PairState.ERROR
            _flight.emit(_flight.PEER_DEATH, self._ftag)
            if self.want_write:
                # same balancing as disconnect(): peer death mid-stall ends
                # the stall — the evidence must say so
                _flight.emit(_flight.WRITE_STALL_END, self._ftag)
        if self.error is None:
            self.error = why
        # Waiters may be blocked in an uncapped select; the state change IS
        # their wake condition, so deliver it.
        self.kick()
        trace_ring.log("pair %s -> ERROR: %s", self.tag, why)

    def _release_channels(self) -> None:
        """Per-connection state: peer windows, notify socket, wakeup pipe, reader
        view.  (Views into regions must drop before regions can close — shm unmap
        refuses while exported pointers exist.)

        Kick FIRST: with the uncapped select (poller.py), a waiter blocked on
        these very fds would otherwise hang forever — closing a registered fd
        silently deregisters it from epoll, delivering nothing. The kick bytes
        are level-readable, so even a waiter mid-gap (between its predicate
        check and the select) wakes and observes the state change; a waiter
        that races the close itself gets EBADF from select, which _wait treats
        as a state-change wakeup."""
        # Detach the async-domain applier hook BEFORE the wake fds close:
        # a record landing mid-teardown must not kick() into a just-closed
        # (and possibly OS-reused) fd number.
        for region in (self.recv_region, self.status_region):
            if region is not None:
                region.on_write = None
        if self._parked or self._park_pending:
            # a parked pair dying mid-park: drop its parked-watcher slot so
            # the poller's map can't accumulate dead stubs (gauge hygiene)
            self._parked = False
            self._park_pending = False
            try:
                from tpurpc.core.poller import Poller

                Poller.forget_parked(self)
            except Exception:
                pass
        self.kick()
        sels, self._selectors = self._selectors, {}
        for sel in sels.values():
            try:
                sel.close()
            except OSError:
                pass
        if self.reader is not None:
            self.reader.release()
            self.reader = None
        self.writer = None
        # Order against _peer_status_pin's re-cache race: null the ATTRIBUTE
        # first (new pins become impossible), then the cache, then close —
        # an in-flight _peer_waiting still pinning through a local is covered
        # by the retry.
        for attr in ("_peer_ring", "_peer_status"):
            w = getattr(self, attr)
            if w is not None:
                setattr(self, attr, None)
                self._peer_status_np = None
                retry_buffer_op(w.close)
        if self.notify_sock is not None:
            try:
                self.notify_sock.close()
            except OSError:
                pass
            self.notify_sock = None
        for pipes in (self._wake_r, self._wake_w):
            for role, fd in pipes.items():
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                    pipes[role] = -1

    def _release_regions(self) -> None:
        # Attribute first, then cache, then close (see _peer-status comment in
        # _release_channels; _status_pin re-checks the attribute after caching).
        for attr in ("recv_region", "status_region"):
            r = getattr(self, attr)
            if r is not None:
                setattr(self, attr, None)
                self._status_np = None
                RingPool.get().forget(r)  # pool-leased (unparked) regions
                r.close()

    def _release_resources(self) -> None:
        self._release_channels()
        self._release_regions()

    def quiesce(self) -> None:
        """Release everything per-connection — channels, peer refs, AND ring
        regions (init() always allocates fresh regions, see its docstring, so an
        idle pooled pair pinning /dev/shm would buy nothing)."""
        if self.state in (PairState.CONNECTED, PairState.HALF_CLOSED):
            self.disconnect()
        self._release_resources()
        self.state = PairState.UNINITIALIZED

    def destroy(self) -> None:
        if self.state in (PairState.CONNECTED, PairState.HALF_CLOSED):
            self.disconnect()
        self._release_resources()
        self.state = PairState.UNINITIALIZED


def create_loopback_pair(ring_size: int = 1 << 16,
                         domain: Optional[MemoryDomain] = None) -> Tuple[Pair, Pair]:
    """Two connected in-process pairs over a unix socketpair — the CI-testable fake
    the reference never wrote (SURVEY.md §4's 'missing fake')."""
    domain = domain or LocalDomain()
    a = Pair(domain, ring_size)
    b = Pair(domain, ring_size)
    a.init()
    b.init()
    sa, sb = socket.socketpair()
    done: List[Optional[BaseException]] = [None]

    def _bside():
        try:
            b.connect_over_socket(sb)
        except BaseException as exc:  # surfaced below
            done[0] = exc

    t = threading.Thread(target=_bside, daemon=True)
    t.start()
    a.connect_over_socket(sa)
    t.join(timeout=10)
    if done[0] is not None:
        raise done[0]
    return a, b
