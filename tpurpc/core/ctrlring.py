"""tpurpc-pulse: shared-memory descriptor rings for the rendezvous control
plane.

PR 9 moved bulk payloads onto the one-sided rendezvous plane — and moved the
waterfall's bottleneck with them: ARCHITECTURE §18 measures ~0.6 ms/message
of control-plane wakeups (COMPLETE frames, notify syscalls, cross-thread
queue handoffs across both processes) against ~0.4 ms for the payload memcpy
itself.  The copy is no longer the cost; the round trips are.  This module
makes the control plane itself ride polled descriptor rings (RDMAbox's
merged-doorbell/batched-I/O discipline, arXiv:2104.12197; the DMA Streaming
Framework's descriptor-ring orchestration, arXiv:2603.10030): per-link
submission/completion rings carved from the same shared-memory domain as the
landing pool, so a steady-state bulk transfer crosses ZERO thread boundaries
— one one-sided payload write plus one 128-byte ring-slot store per message,
no frame encode/decode, no fd kicks, no parked-thread handoffs.

Layout — one ring per direction, each owned by its CONSUMER (the side that
reads it allocates it and advertises the handle in the PING-hello capability
blob; the producer opens a window onto it):

    header (64 B): magic, version, nslots, slot_bytes,
                   cons_head (u64 — the ring's DOORBELL word: consumed
                   count, published by the consumer once per drained BATCH,
                   exactly PR 9's consumer-done gate),
                   parked (u32 — consumer-is-blocked flag, the futex-style
                   handshake), nonce (16 B anti-mixup, as for landing
                   regions)
    slots  (nslots × slot_bytes): seq-stamped records
        [stamp u64][frame_seq u64][stream_id u32][len u16][op u8][flags u8]
        [payload ≤ slot_bytes-24]

Protocol (modeled exhaustively in ``analysis/ringcheck.py check_ctrlring``;
mutants ``ctrl_publish_before_write``, ``ctrl_reuse_before_doorbell`` and
``ctrl_park_no_redrain`` are all killed):

* the producer writes a slot's payload and fields FIRST and the ``stamp``
  (seq+1) LAST — a reader that observes the stamp observes a whole record;
* a slot is reused only after the consumer's published ``cons_head`` covers
  its previous lap (``seq - cons_head < nslots`` before any store) — the
  ring-full case falls back to the framed control path, never overwrites;
* lost-wakeup close: the producer stores the stamp, THEN reads ``parked``
  and sends one framed kick when set; the consumer sets ``parked``, THEN
  re-drains once before blocking.  Either order of the race delivers.

Ordering with the framed path: every record carries ``frame_seq`` — the
count of frames its sender had written when posting — and the consumer
processes a record only once it has dispatched that many frames.  A control
op posted after a framed MESSAGE on the same stream therefore lands after
it, and vice versa (the consumer drains the ring before dispatching each
frame), so per-stream delivery order survives the split control plane.

Negotiation rides the existing PING-hello: each side appends its receive
ring's descriptor to the rendezvous hello payload.  Un-negotiated peers
(the native C plane, h2 planes, older builds), non-host-addressable domains
and cross-host handles (nonce mismatch) keep the framed control path — the
PR 9 fallback ladder is untouched, and every ring failure (full, closed,
oversized payload) degrades to a framed send, never a lost op.

Env knobs: ``TPURPC_CTRL_RING`` (default on), ``TPURPC_CTRL_RING_SLOTS``
(default 64).
"""

from __future__ import annotations

import os
import struct
import time
from typing import Callable, Dict, Optional, Tuple

from tpurpc.analysis.locks import make_lock
from tpurpc.core import pair as _pair
from tpurpc.core import transport as _transport
from tpurpc.obs import flight as _flight
from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.utils import stats as _stats

# tpurpc-lens frame markers: a thread polling/draining/posting descriptor
# rings is doing control-plane work — the waterfall's `ctrl` hop carries
# the bytes, these carry the CPU attribution
_LENS_STAGES = {
    "read_frame_polled": "ctrl-ring",
    "drain": "ctrl-ring",
    "post": "ctrl-ring",
}
_profiler.register_stages(__file__, _LENS_STAGES)

__all__ = [
    "CtrlRing", "CtrlPeer", "CtrlPlane", "enabled", "read_frame_polled",
    "TEST_HOOKS", "SLOT_BYTES", "MAX_CTRL_PAYLOAD",
]

# tpurpc-lens: control-plane work (ring posts/drains AND framed control
# sends) is its own waterfall hop — carrying a few hundred bytes per bulk
# message it can never trip the slowest-hop argmin (the <1%-of-bulk-bytes
# rule), but its busy share is exactly the collapse this PR must make
# visible per hop instead of inferring from wall clock
_LENS_CTRL_BYTES, _LENS_CTRL_NS, _LENS_CTRL_COPY = _lens.hop_counters("ctrl")

_POSTS = _metrics.counter("ctrl_ring_posts")
_RECORDS = _metrics.counter("ctrl_ring_records")
_KICKS = _metrics.counter("ctrl_ring_kicks")
_FULL = _metrics.counter("ctrl_ring_full_fallbacks")

#: scrape-time truth for the watchdog's `ctrl-ring` stage: records posted
#: into peers' rings that their consumers have not yet drained
_BACKLOG = _metrics.fleet("ctrl_ring_backlog", lambda p: p.backlog())

#: test seams (tests/test_ctrlring.py, tools/ctrlring_smoke.py):
#: ``freeze_drain`` makes every consumer's drain a no-op — posted records
#: age in the ring, the induced stuck-ring stall the watchdog must name
TEST_HOOKS: Dict[str, object] = {}

_MAGIC = 0x54504352  # 'TPCR'
_VERSION = 1
SLOT_BYTES = 128
_NONCE_BYTES = 16

#: header: magic, version, nslots, slot_bytes, cons_head, parked, pad, nonce
_HDR = struct.Struct("<IIIIQII16s")
_HDR_BYTES = 64
_CONS_HEAD = struct.Struct("<Q")
_CONS_HEAD_OFF = 16
_PARKED = struct.Struct("<I")
_PARKED_OFF = 24
_NONCE_OFF = 32

#: slot record header; the stamp (first u64) is stored SEPARATELY, last
_SLOT_HDR = struct.Struct("<QQIHBB")
_SLOT_HDR_BYTES = _SLOT_HDR.size  # 24
_STAMP = struct.Struct("<Q")
MAX_CTRL_PAYLOAD = SLOT_BYTES - _SLOT_HDR_BYTES

#: hello-blob framing: u16 length prefix + descriptor
_BLOB_LEN = struct.Struct("<H")
_DESC = struct.Struct("<IIQ16sB")  # nslots, slot_bytes, nbytes, nonce, klen


def enabled() -> bool:
    return os.environ.get("TPURPC_CTRL_RING", "1").lower() not in (
        "0", "off", "false")


def _default_slots() -> int:
    try:
        return max(8, int(os.environ.get("TPURPC_CTRL_RING_SLOTS", "64")))
    except ValueError:
        return 64


class CtrlRing:
    """The consumer-owned half: allocates the shm region, drains records,
    publishes ``cons_head`` once per batch, owns the ``parked`` word."""

    #: lint rule `lock`: the drain cursor and closed flag are shared
    #: between whichever thread holds the drain lock and the close path
    _GUARDED_BY = {"head": "_lock", "closed": "_lock"}

    def __init__(self, kind: str = "shm", nslots: Optional[int] = None):
        self.kind = kind
        self.nslots = nslots or _default_slots()
        self.slot_bytes = SLOT_BYTES
        self.nonce = os.urandom(_NONCE_BYTES)
        self._domain = _pair.make_domain(kind)
        self.nbytes = _HDR_BYTES + self.nslots * self.slot_bytes
        self.region = self._domain.alloc(self.nbytes)
        self.head = 0          # consumed count (local truth)
        self._published = 0    # last cons_head stored into the header
        self.closed = False
        self._lock = make_lock("CtrlRing._lock")
        _HDR.pack_into(self.region.buf, 0, _MAGIC, _VERSION, self.nslots,
                       self.slot_bytes, 0,
                       1,  # parked: nobody polls until a reader adopts us
                       0, self.nonce)

    def descriptor(self) -> bytes:
        """The hello-blob descriptor the producer opens a window with."""
        kb = self.kind.encode()
        return (_DESC.pack(self.nslots, self.slot_bytes, self.nbytes,
                           self.nonce, len(kb))
                + kb + self.region.handle.encode())

    # -- consumer side --------------------------------------------------------

    def set_parked(self, parked: bool) -> None:
        with self._lock:
            if self.closed:
                return
            _PARKED.pack_into(self.region.buf, _PARKED_OFF,
                              1 if parked else 0)

    def drain(self, on_op: Callable[[int, int, object], None],
              frames_dispatched: Callable[[], int]) -> int:
        """Consume every ready record in ONE pass (the batched-completion
        fast path: the Python consumer observes completed batches, one
        ``cons_head`` publish per batch).  A record whose ``frame_seq``
        outruns the dispatched-frame count is left in place — the frames it
        must order after are still in flight.  Concurrent drainers skip
        (try-lock): records dispatch in slot order, exactly once."""
        if TEST_HOOKS.get("freeze_drain"):
            return 0
        if not self._lock.acquire(blocking=False):
            return 0
        try:
            if self.closed:
                return 0
            buf = self.region.buf
            n = 0
            t0 = time.monotonic_ns()
            nbytes = 0
            while True:
                slot = _HDR_BYTES + (self.head % self.nslots) \
                    * self.slot_bytes
                (stamp,) = _STAMP.unpack_from(buf, slot)
                if stamp != self.head + 1:
                    break
                (_stamp, frame_seq, stream_id, ln, op,
                 _flags) = _SLOT_HDR.unpack_from(buf, slot)
                if frame_seq > frames_dispatched():
                    break  # ordered after frames still in flight
                payload = bytes(buf[slot + _SLOT_HDR_BYTES:
                                    slot + _SLOT_HDR_BYTES + ln])
                # _lock IS held — acquired nonblocking above (the lint's
                # with-statement pattern can't see a try-acquire/finally)
                self.head += 1  # tpr: allow(lock)
                n += 1
                nbytes += ln
                on_op(op, stream_id, payload)
            if n:
                # one doorbell store per drained batch — the consumer-done
                # gate the producer's full-check reads through its window
                _CONS_HEAD.pack_into(buf, _CONS_HEAD_OFF, self.head)
                self._published = self.head
                _RECORDS.inc(n)
                dt = time.monotonic_ns() - t0
                _LENS_CTRL_BYTES.inc(nbytes)
                _LENS_CTRL_NS.inc(dt)
                _stats.batch_hist("ctrl_ring_batch").record(n)
            return n
        finally:
            self._lock.release()

    def close(self) -> None:
        """Link death/teardown.  The region is released on OUR side only —
        a straggling producer still holds its window and may land a late
        slot store, which hits the orphaned mapping (dead memory), never a
        ring re-advertised to a new link: rings are per-connection and
        never pooled (Pair.init's stale-write rule)."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
        try:
            _pair.retry_buffer_op(self.region.buf.release, timeout_s=0.5)
            self.region._close()
        except Exception:
            pass  # the OS reclaims the mapping with the process


class CtrlPeer:
    """The producer half: a window onto the peer's receive ring.  ``post``
    returns 0 (not posted — framed fallback), 1 (posted) or 2 (posted AND
    the consumer is parked — send one framed kick)."""

    _GUARDED_BY = {"seq": "_lock", "closed": "_lock", "_stalled": "_lock"}

    def __init__(self, kind: str, handle: str, nslots: int, slot_bytes: int,
                 nbytes: int, nonce: bytes, ftag: int = 0):
        if slot_bytes != SLOT_BYTES:
            raise ValueError(f"peer ring slot_bytes {slot_bytes} != "
                             f"{SLOT_BYTES}")
        domain = _pair.make_domain(kind)
        self._win = domain.open_window(handle, nbytes)
        view = self._win.view
        if view is None:
            self._win.close()
            raise OSError("ctrl ring needs a host-addressable window "
                          f"(domain {kind!r} has none)")
        (magic, version, r_nslots, r_slot_bytes, _head, _parked, _pad,
         r_nonce) = _HDR.unpack_from(view, 0)
        if (magic != _MAGIC or version != _VERSION or r_nslots != nslots
                or r_slot_bytes != slot_bytes or r_nonce != nonce):
            self._win.close()
            raise OSError("ctrl ring descriptor mismatch: the advertised "
                          "handle resolves to different memory on this "
                          "host")
        self.view = view
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self.seq = 0        # next record index (stamp = seq+1)
        self.closed = False
        self._stalled = False  # ring-full edge (flight stall bracket)
        self._ftag = ftag
        self._lock = make_lock("CtrlPeer._lock")
        _BACKLOG.track(self)

    def backlog(self) -> int:
        """Records posted but not yet consumed by the peer (the fleet
        gauge the watchdog's `ctrl-ring` stage reads)."""
        if self.closed:
            return 0
        try:
            (head,) = _CONS_HEAD.unpack_from(self.view, _CONS_HEAD_OFF)
        except (ValueError, struct.error):
            return 0
        return max(0, self.seq - head)

    def post(self, op: int, stream_id: int, payload: bytes,
             frame_seq: int) -> int:
        if len(payload) > MAX_CTRL_PAYLOAD:
            return 0
        with self._lock:
            if self.closed:
                return 0
            view = self.view
            try:
                (head,) = _CONS_HEAD.unpack_from(view, _CONS_HEAD_OFF)
            except (ValueError, struct.error):
                return 0
            if self.seq - head >= self.nslots:
                # ring full: degrade to the framed path (never overwrite an
                # unconsumed slot).  The full→not-full transition is a
                # flight-bracketed stall edge — aged open, it is the
                # watchdog's evidence the consumer stopped draining.
                if not self._stalled:
                    self._stalled = True
                    _flight.emit(_flight.CTRL_STALL_BEGIN, self._ftag,
                                 self.seq - head)
                _FULL.inc()
                return 0
            if self._stalled:
                self._stalled = False
                _flight.emit(_flight.CTRL_STALL_END, self._ftag, 0)
            slot = _HDR_BYTES + (self.seq % self.nslots) * self.slot_bytes
            # payload and fields FIRST ...
            view[slot + _SLOT_HDR_BYTES:
                 slot + _SLOT_HDR_BYTES + len(payload)] = payload
            _SLOT_HDR.pack_into(view, slot, 0, frame_seq, stream_id,
                                len(payload), op, 0)
            # ... the stamp LAST: a consumer that observes it observes a
            # whole record (the publish-after-write discipline the
            # ctrl_publish_before_write mutant inverts)
            _STAMP.pack_into(view, slot, self.seq + 1)
            self.seq += 1
            _POSTS.inc()
            # parked is read strictly AFTER the stamp store: either the
            # consumer's park-then-redrain sees our record, or we see its
            # parked flag and kick — the lost-wakeup race has no third leg
            try:
                (parked,) = _PARKED.unpack_from(view, _PARKED_OFF)
            except (ValueError, struct.error):
                parked = 1
            return 2 if parked else 1

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
        try:
            self._win.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# The per-connection plane: rx + tx + the adaptive poll/park state.
# ---------------------------------------------------------------------------

#: consumer-side adaptive gate, the poller's activity-EWMA discipline
#: (core/poller.py) applied to ring polling: drains that find records are
#: hits, empty probes are misses; below the floor the consumer PARKS on the
#: framed path (fd wakeups) and the producer's kick re-heats it.
_EWMA_HIT = 0.5
_EWMA_MISS = 0.7
_EWMA_FLOOR = 0.1


class CtrlPlane:
    """One connection's descriptor-ring control plane: the locally owned
    receive ring (advertised in the hello), the window onto the peer's
    (opened from the peer's hello), and the consumer's hot/parked state.
    ``armed`` flips exactly once, when the peer's descriptor verifies —
    until then (and forever, for un-negotiated peers) every control op
    stays framed."""

    def __init__(self, name: str, kind: str = "shm"):
        self._ftag = _flight.tag_for("ctrl:" + name)
        self.rx: Optional[CtrlRing] = None
        self.tx: Optional[CtrlPeer] = None
        self.armed = False
        self._ewma = 0.0       # cold start: parked until the first hit
        self._mode_hot = False
        self._closed = False
        try:
            self.rx = CtrlRing(kind=kind)
        except Exception:
            self.rx = None  # no shm on this host: framed control forever

    # -- negotiation ----------------------------------------------------------

    def hello_blob(self) -> bytes:
        """Appended to the rendezvous HELLO_PAYLOAD: this side's receive
        ring descriptor (empty when ring control is off/unavailable)."""
        if self.rx is None or not enabled():
            return b""
        desc = self.rx.descriptor()
        return _BLOB_LEN.pack(len(desc)) + desc

    def on_hello(self, blob: bytes) -> bool:
        """Parse the peer's descriptor and open the submission window.
        Any failure — empty blob (peer predates rings / disabled), a
        handle this host cannot open (cross-host TCP), a nonce mismatch —
        leaves the link framed.  Returns True on adoption."""
        if self.armed or self._closed or not blob or not enabled():
            return False
        try:
            (nslots, slot_bytes, nbytes, nonce,
             klen) = _DESC.unpack_from(blob, _BLOB_LEN.size)
            pos = _BLOB_LEN.size + _DESC.size
            kind = blob[pos:pos + klen].decode()
            handle = blob[pos + klen:].decode()
            self.tx = CtrlPeer(kind, handle, nslots, slot_bytes, nbytes,
                               nonce, ftag=self._ftag)
        except Exception:
            return False
        self.armed = True
        _flight.emit(_flight.CTRL_ADOPT, self._ftag, nslots, slot_bytes)
        return True

    # -- producer face --------------------------------------------------------

    def post(self, op: int, stream_id: int, payload: bytes, frame_seq: int,
             kick: Callable[[], None]) -> bool:
        """Post one control op to the peer's ring; True when placed (the
        framed path must NOT also send it).  A parked consumer gets one
        framed kick — the only frame a cold→hot transition costs."""
        tx = self.tx
        if tx is None or not self.armed:
            return False
        t0 = time.monotonic_ns()
        r = _transport.dispatch("post", self, tx.post, op, stream_id,
                                payload, frame_seq)
        if not r:
            return False
        n = len(payload)
        dt = time.monotonic_ns() - t0
        _LENS_CTRL_BYTES.inc(n)
        _LENS_CTRL_NS.inc(dt)
        if r == 2:
            _KICKS.inc()
            try:
                _transport.dispatch("kick", self, kick)
            except Exception:
                pass  # connection dying; the framed paths surface it
        return True

    # -- consumer face --------------------------------------------------------

    def drain(self, on_op: Callable[[int, int, object], None],
              frames_dispatched: Callable[[], int]) -> int:
        rx = self.rx
        if rx is None:
            return 0
        n = rx.drain(on_op, frames_dispatched)
        if n:
            self._ewma = self._ewma + _EWMA_HIT * (1.0 - self._ewma)
            if not self._mode_hot:
                self._mode_hot = True
                _flight.emit(_flight.CTRL_SPIN, self._ftag, rx.head)
        return n

    def note_miss(self) -> None:
        self._ewma *= _EWMA_MISS

    def hot(self) -> bool:
        return self._ewma >= _EWMA_FLOOR

    def park(self) -> None:
        """About to block on the framed path: raise the parked flag so the
        producer's next post kicks us.  The caller MUST re-drain once
        after this (the lost-wakeup close the ctrl_park_no_redrain mutant
        removes)."""
        rx = self.rx
        if rx is not None:
            rx.set_parked(True)
        if self._mode_hot:
            self._mode_hot = False
            _flight.emit(_flight.CTRL_PARK, self._ftag,
                         rx.head if rx is not None else 0)

    def unpark(self) -> None:
        rx = self.rx
        if rx is not None:
            rx.set_parked(False)

    def backlog(self) -> int:
        tx = self.tx
        return tx.backlog() if tx is not None else 0

    def close(self) -> None:
        self._closed = True
        self.armed = False
        tx, self.tx = self.tx, None
        if tx is not None:
            tx.close()
        rx, self.rx = self.rx, None
        if rx is not None:
            rx.close()


# ---------------------------------------------------------------------------
# The polled read loop shared by every connection reader/pump.
# ---------------------------------------------------------------------------

#: how long one framed-read probe blocks while the link is HOT — the upper
#: bound on ring-record latency while frames are idle, and the slice that
#: yields the core to the producer on a single-hart host
_HOT_SLICE_S = 0.0005
#: cheap scheduler-yield probes between drain attempts before paying a
#: framed-read slice: a producer mid-memcpy posts within a few yields
_YIELD_SPINS = 8


def read_frame_polled(read_frame, drain: Callable[[], int],
                      plane: CtrlPlane, timeout: Optional[float] = None,
                      should_stop: Optional[Callable[[], bool]] = None):
    """``read_frame`` with the descriptor-ring poll/park discipline.

    HOT (recent drains): alternate ring drains with scheduler yields and
    short framed-read slices — records are consumed in batches with no fd
    wakeups, frames still flow.  COLD (EWMA below floor): raise the parked
    flag, re-drain once, and block on the framed read — the producer's
    kick (or any frame) wakes us.  ``should_stop`` (inline-pump callers:
    "my predicate is satisfied") raises ReadTimeout so the pump re-checks.

    Returns whatever ``read_frame`` returns (Frame/CONSUMED/None); raises
    ReadTimeout past ``timeout``.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        drained = drain()
        if should_stop is not None and should_stop():
            raise _pair_ReadTimeout()
        if drained or plane.hot():
            if not drained:
                spins = 0
                while spins < _YIELD_SPINS:
                    spins += 1
                    time.sleep(0)
                    if drain():
                        break
                    if should_stop is not None and should_stop():
                        raise _pair_ReadTimeout()
            slice_s = _HOT_SLICE_S
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise _pair_ReadTimeout()
                slice_s = min(slice_s, remain)
            try:
                f = read_frame(timeout=slice_s)
            except TimeoutError:
                plane.note_miss()
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                continue
            # a record posted BEFORE this frame was sent is visible in shm
            # by store order — deliver it first, so per-stream order holds
            # across the ring/framed split
            drain()
            return f
        # cold: park on the framed path (fd wakeups); the mandatory
        # re-drain closes the park/post race — a record posted before our
        # flag store is found here, one posted after sees the flag and
        # kicks
        plane.park()
        try:
            if drain():
                plane.unpark()
                continue
            if should_stop is not None and should_stop():
                raise _pair_ReadTimeout()
            remain = (None if deadline is None
                      else max(0.0, deadline - time.monotonic()))
            f = read_frame(timeout=remain)
            drain()  # ring records posted before this frame deliver first
            return f
        finally:
            plane.unpark()


def _pair_ReadTimeout():
    from tpurpc.core.endpoint import ReadTimeout

    return ReadTimeout()
