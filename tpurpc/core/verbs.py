"""Verbs (RDMA NIC) memory domain — the hardware one-sided-placement
skeleton's Python half (VERDICT r4 missing #3).

The reference's defining capability is the NIC writing the receive ring
with zero receiver CPU (``ibverbs/pair.cc:587-622`` postWrite over
``ibv_reg_mr``'d buffers). tpurpc reaches hardware through its
:class:`~tpurpc.core.pair.MemoryDomain` seam instead: this domain
allocates NIC-registered regions and opens windows whose ``write`` is an
RDMA WRITE — the same Region/Window contract the shm and tcp_window
domains implement in software, so the whole pair/poller/endpoint stack
above is untouched.

Native half: ``native/src/verbs_domain.cc`` — compiled against real
libibverbs where ``<infiniband/verbs.h>`` exists, honest "unavailable"
stubs otherwise (``make_domain("verbs")`` then raises a RuntimeError
naming the missing capability instead of faking placement). CI proves
the real call sequence against ``tests/mock_verbs`` (an in-process
verbs.h whose RDMA WRITE is a registry-backed memcpy with rkey/bounds
checks and QP-state order checks).

Rendezvous: ``alloc`` registers the region AND creates its RC queue
pair, embedding ``rkey/addr/qpn/lid/gid/psn`` in the region handle (the
reference's Address carries lid/qpn/psn/gid the same way,
``address.h:24-31``); ``open_window`` creates the writer-side QP and
connects it to those attrs. This is also what makes the domain a
tpurpc-express landing-pool backend (ISSUE 9,
``core/rendezvous.py LandingPool("verbs")``): a bulk-tensor CLAIM
carries the verbs handle and the sender's one-sided payload write IS an
RDMA WRITE into the registered landing region. Two verbs-specific
consequences: the window exposes no host-readable ``view``, so the
standing-region doorbell (consumer-done word read through the window)
is unavailable and steady-state reuse stays on explicit grant frames;
and the per-region write path rides the bounce-MR staging below, one
post per gather segment. The reverse leg — the region owner
connecting ITS QP to the writer's attrs, which real RC hardware requires
before the first WRITE lands — is :meth:`VerbsDomain.accept_writer`, the
integration point the pair bootstrap's capability negotiation calls
(``core/pair.py`` ``Address.caps``); the in-process mock delivers
without it, so the E2E wiring remains a hardware-bringup task and is
documented as such rather than silently absent.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Tuple

from tpurpc.core.pair import MemoryDomain, Region, Window, register_domain
from tpurpc.obs import metrics as _metrics


class VerbsWindow(Window):
    """Window plus the writer-side QP attrs (Window declares __slots__):
    the pair bootstrap ships these back to the region owner for
    :meth:`VerbsDomain.accept_writer`, the reverse RC leg."""

    __slots__ = ("writer_attrs",)

_LIB = None
_LIB_LOCK = threading.Lock()

#: registrations currently parked in MR caches, and cumulative cache hits
#: (gauges so tools/scale_smoke.py and /metrics read them without a
#: registry walk; hits only ever grows — it is a counter wearing a gauge
#: face because the ISSUE 16 scrape contract names it with the gauges)
_MR_CACHE_ENTRIES = _metrics.gauge("mr_cache_entries")
_MR_CACHE_HITS = _metrics.gauge("mr_cache_hits")


def _size_class(nbytes: int) -> int:
    """Power-of-two round-up with a page floor — the cache key. Rounding
    means a 12 KiB ring and a 16 KiB ring share the 16 KiB class, which
    is the whole point: 10k pairs hold O(size-classes) distinct
    registration shapes, not O(pairs)."""
    return max(4096, 1 << max(nbytes - 1, 1).bit_length())


class _MRCache:
    """Size-classed free list of NIC registrations (``ibv_reg_mr``
    results) owned by one :class:`VerbsDomain` (MRs belong to a PD — a
    registration can never migrate between device contexts).

    Registration is the expensive, page-pinning verb (µs-scale kernel
    round-trip + IOMMU work). At C100K churn — pairs parking/unparking,
    rendezvous windows cycling through the per-link cache — deregistering
    on every close and re-registering on every open is O(events)
    registrations. This cache makes it O(size-classes): ``lease`` pops a
    parked MR of the right class or registers a fresh one, ``release``
    parks it again instead of deregistering. Leased MRs are exclusively
    owned by the leaseholder (a bounce MR is staged into concurrently —
    sharing one between two live windows would interleave their staging
    copies); the refcounted *window* sharing that lets many pairs reuse
    one live registration sits above this in
    ``rendezvous._WindowShare``.

    Bounded two ways (entries per class, total parked bytes) so a burst
    of huge landing regions cannot pin memory forever; overflow falls
    back to the plain dereg path."""

    _GUARDED_BY = {"_free": "_lock", "_free_bytes": "_lock",
                   "hits": "_lock", "misses": "_lock"}

    _MAX_PER_CLASS = 64
    _MAX_FREE_BYTES = 256 << 20

    def __init__(self, lib, ctx):
        self._lib = lib
        self._ctx = ctx
        self._lock = threading.Lock()
        self._free: Dict[int, List[int]] = {}   # class bytes -> [mr, ...]
        self._free_bytes = 0
        self.hits = 0
        self.misses = 0

    def lease(self, nbytes: int) -> Tuple[int, int]:
        """Return ``(mr, class_bytes)`` with ``class_bytes >= nbytes``.
        The backing memory is zeroed on a cache hit — a recycled
        registration still holds the previous tenant's bytes, and a fresh
        RingReader parsing a stale frame header is exactly the corruption
        class RingPool zeroes against."""
        cls = _size_class(nbytes)
        with self._lock:
            lst = self._free.get(cls)
            if lst:
                mr = lst.pop()
                self._free_bytes -= cls
                self.hits += 1
                _MR_CACHE_HITS.inc()
                _MR_CACHE_ENTRIES.dec()
                ctypes.memset(self._lib.tpr_verbs_mr_addr(mr), 0, cls)
                return mr, cls
            self.misses += 1
        mr = self._lib.tpr_verbs_reg(self._ctx, None, cls)
        if not mr:
            raise MemoryError(f"ibv_reg_mr failed ({cls} bytes)")
        return mr, cls

    def release(self, mr: int, cls: int) -> None:
        with self._lock:
            lst = self._free.setdefault(cls, [])
            if (len(lst) < self._MAX_PER_CLASS
                    and self._free_bytes + cls <= self._MAX_FREE_BYTES):
                lst.append(mr)
                self._free_bytes += cls
                _MR_CACHE_ENTRIES.inc()
                return
        self._lib.tpr_verbs_dereg(mr)

    def drain(self) -> None:
        """Dereg every parked registration (domain close — the PD is
        about to go away and real hardware refuses dealloc_pd under live
        MRs)."""
        with self._lock:
            mrs = [mr for lst in self._free.values() for mr in lst]
            self._free.clear()
            self._free_bytes = 0
            _MR_CACHE_ENTRIES.dec(len(mrs))
        for mr in mrs:
            self._lib.tpr_verbs_dereg(mr)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"free_entries": sum(len(v) for v in self._free.values()),
                    "free_bytes": self._free_bytes,
                    "hits": self.hits, "misses": self.misses}


def _load():
    """The verbs symbols live in libtpurpc.so (stub or real); tests point
    TPURPC_VERBS_LIB at a mock-fabric build."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.environ.get("TPURPC_VERBS_LIB") or os.environ.get(
            "TPURPC_NATIVE_LIB",
            os.path.join(here, "native", "build", "libtpurpc.so"))
        lib = ctypes.CDLL(path)
        lib.tpr_verbs_available.restype = ctypes.c_int
        lib.tpr_verbs_open.restype = ctypes.c_void_p
        lib.tpr_verbs_open.argtypes = [ctypes.c_char_p]
        lib.tpr_verbs_close.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_reg.restype = ctypes.c_void_p
        lib.tpr_verbs_reg.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_size_t]
        lib.tpr_verbs_mr_addr.restype = ctypes.c_void_p
        lib.tpr_verbs_mr_addr.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_mr_len.restype = ctypes.c_uint64
        lib.tpr_verbs_mr_len.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_mr_lkey.restype = ctypes.c_uint32
        lib.tpr_verbs_mr_lkey.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_mr_rkey.restype = ctypes.c_uint32
        lib.tpr_verbs_mr_rkey.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_dereg.argtypes = [ctypes.c_void_p]
        lib.tpr_verbs_qp_create.restype = ctypes.c_void_p
        lib.tpr_verbs_qp_create.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint16), ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.tpr_verbs_qp_connect.restype = ctypes.c_int
        lib.tpr_verbs_qp_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint16,
            ctypes.c_char_p, ctypes.c_uint32]
        lib.tpr_verbs_write.restype = ctypes.c_int
        lib.tpr_verbs_write.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint64]
        lib.tpr_verbs_qp_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


class VerbsDomain(MemoryDomain):
    """NIC-registered regions + RDMA-WRITE windows (skeleton)."""

    kind = "verbs"

    def __init__(self, device: Optional[str] = None):
        lib = _load()
        if not lib.tpr_verbs_available():
            raise RuntimeError(
                "verbs domain: libibverbs/RDMA NIC not available on this "
                "host (the build compiled the unavailable stubs). The shm "
                "and tcp_window domains carry the same one-sided protocol "
                "in software; this skeleton activates where "
                "<infiniband/verbs.h> and a NIC exist.")
        self._lib = lib
        self._ctx = lib.tpr_verbs_open(
            device.encode() if device else None)
        if not self._ctx:
            raise RuntimeError("verbs domain: no RDMA device opened")
        self._lock = threading.Lock()
        #: region handle -> (mr, receiver-side qp, size class) —
        #: accept_writer connects the qp once the writer's attrs arrive
        #: via the bootstrap; the class routes close back to the MR cache
        self._regions: Dict[str, Tuple[int, int, int]] = {}
        #: shared registration cache — alloc'd regions AND window bounce
        #: buffers lease from here, so pair park/unpark and rendezvous
        #: window churn recycle O(size-classes) registrations
        self.mr_cache = _MRCache(lib, self._ctx)

    def close(self) -> None:
        """Release the device context (PD + CQ + device). Still-open
        regions are torn down FIRST (real hardware refuses to dealloc a
        PD with live MRs — closing the ctx under them would leak the
        pinned memory and leave Region.close poking freed state); their
        later Region.close() calls become no-ops via the registry pop.
        Idempotent."""
        with self._lock:
            leftovers = list(self._regions.items())
            self._regions.clear()
        for _handle, (mr, qp, _cls) in leftovers:
            self._lib.tpr_verbs_qp_destroy(qp)
            self._lib.tpr_verbs_dereg(mr)
        self.mr_cache.drain()
        ctx, self._ctx = self._ctx, None
        if ctx:
            self._lib.tpr_verbs_close(ctx)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: modules may be half-dead

    # -- MemoryDomain contract ----------------------------------------------

    def alloc(self, nbytes: int) -> Region:
        lib = self._lib
        # lease a (possibly recycled) registration: the MR backs cls
        # bytes >= nbytes, the handle advertises the logical nbytes
        mr, cls = self.mr_cache.lease(nbytes)
        addr = lib.tpr_verbs_mr_addr(mr)
        rkey = lib.tpr_verbs_mr_rkey(mr)
        qpn = ctypes.c_uint32()
        lid = ctypes.c_uint16()
        gid = ctypes.create_string_buffer(16)
        psn = ctypes.c_uint32()
        qp = lib.tpr_verbs_qp_create(self._ctx, ctypes.byref(qpn),
                                     ctypes.byref(lid), gid,
                                     ctypes.byref(psn))
        if not qp:
            self.mr_cache.release(mr, cls)
            raise RuntimeError("verbs qp_create failed")
        handle = (f"verbs:{rkey}:{addr}:{nbytes}:{qpn.value}:{lid.value}:"
                  f"{gid.raw.hex()}:{psn.value}")
        buf = (ctypes.c_uint8 * nbytes).from_address(addr)
        with self._lock:
            self._regions[handle] = (mr, qp, cls)

        def _close():
            with self._lock:
                entry = self._regions.pop(handle, None)
            if entry:
                # the QP is peer-state and dies with the region; the
                # REGISTRATION is the expensive part and goes back to
                # the pool for the next same-class alloc
                lib.tpr_verbs_qp_destroy(entry[1])
                self.mr_cache.release(entry[0], entry[2])

        return Region(handle, buf, _close)

    def accept_writer(self, region_handle: str, writer_qpn: int,
                      writer_lid: int, writer_gid: bytes,
                      writer_psn: int) -> None:
        """Reverse RC leg: connect the REGION's queue pair to the writer's
        attrs (real hardware requires both halves in RTR/RTS before the
        first WRITE; the pair bootstrap calls this when the peer's window
        attrs arrive in its Address blob)."""
        with self._lock:
            entry = self._regions.get(region_handle)
        if entry is None:
            raise KeyError(f"no such region {region_handle!r}")
        rc = self._lib.tpr_verbs_qp_connect(
            entry[1], writer_qpn, writer_lid, bytes(writer_gid),
            writer_psn)
        if rc != 0:
            raise RuntimeError("verbs accept_writer: qp_connect failed")

    def open_window(self, handle: str, nbytes: int) -> Window:
        parts = handle.split(":")
        if len(parts) != 8 or parts[0] != "verbs":
            raise ValueError(f"not a verbs handle: {handle!r}")
        _, rkey_s, addr_s, len_s, qpn_s, lid_s, gid_hex, psn_s = parts
        rkey, base, rlen = int(rkey_s), int(addr_s), int(len_s)
        if nbytes > rlen:
            raise ValueError(f"window {nbytes} exceeds region {rlen}")
        lib = self._lib
        qpn = ctypes.c_uint32()
        lid = ctypes.c_uint16()
        gid = ctypes.create_string_buffer(16)
        psn = ctypes.c_uint32()
        qp = lib.tpr_verbs_qp_create(self._ctx, ctypes.byref(qpn),
                                     ctypes.byref(lid), gid,
                                     ctypes.byref(psn))
        if not qp:
            raise RuntimeError("verbs qp_create failed")
        if lib.tpr_verbs_qp_connect(qp, int(qpn_s), int(lid_s),
                                    bytes.fromhex(gid_hex),
                                    int(psn_s)) != 0:
            lib.tpr_verbs_qp_destroy(qp)
            raise RuntimeError("verbs qp_connect failed")
        #: the writer's own attrs — the pair bootstrap ships these back to
        #: the region owner for accept_writer (the reverse RC leg)
        local_attrs = (qpn.value, lid.value, gid.raw, psn.value)

        # Registered-source post path: real RC hardware only accepts a
        # WRITE whose local SGE sits inside an ibv_reg_mr'd buffer carrying
        # that MR's lkey — posting from arbitrary user memory is a local
        # protection fault, not a slow path. Writes stage through a
        # window-sized registered bounce MR and post with its real lkey.
        # (The reference's SendZerocopy instead reg_mr's user buffers on
        # the fly, pair.cc:793-941; a persistent bounce trades ONE staging
        # copy per write for zero per-write registrations — registration
        # is µs-scale and pins pages, the wrong trade for a window written
        # repeatedly.) Staging is offset-mapped (window offset == bounce
        # offset), so concurrent writes to disjoint spans don't collide.
        # The bounce is LEASED from the MR cache — a live bounce is
        # exclusively this window's (two windows staging into one buffer
        # would interleave), but close returns the registration for the
        # next window of the same size class instead of deregistering.
        try:
            bounce, bounce_cls = self.mr_cache.lease(nbytes)
        except MemoryError:
            lib.tpr_verbs_qp_destroy(qp)
            raise MemoryError("verbs open_window: bounce ibv_reg_mr failed")
        bounce_lkey = lib.tpr_verbs_mr_lkey(bounce)
        bounce_addr = lib.tpr_verbs_mr_addr(bounce)
        staging = memoryview((ctypes.c_uint8 * nbytes).from_address(
            bounce_addr)).cast("B")

        def write(offset: int, data) -> None:
            view = memoryview(data).cast("B")
            n = len(view)
            # enforce the WINDOW the caller opened, not the whole region —
            # nbytes would otherwise be open-time decoration
            if offset < 0 or offset + n > nbytes:
                raise IndexError(f"write [{offset}, {offset + n}) outside "
                                 f"window of {nbytes}")
            staging[offset:offset + n] = view  # the one staging copy
            if self._lib.tpr_verbs_write(
                    qp, ctypes.c_void_p(bounce_addr + offset), bounce_lkey,
                    base + offset, rkey, n) != 0:
                raise OSError("RDMA WRITE failed")

        def close() -> None:
            staging.release()  # drop the alias before the MR changes hands
            self.mr_cache.release(bounce, bounce_cls)
            lib.tpr_verbs_qp_destroy(qp)

        w = VerbsWindow(write, close)
        w.writer_attrs = local_attrs  # bootstrap seam (accept_writer)
        return w


register_domain("verbs", VerbsDomain)
