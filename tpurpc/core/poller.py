"""Background poller threads + pair pool — the hybrid (BPEV) machinery.

Reference: ``src/core/lib/ibverbs/poller.{h,cc}`` — N dedicated busy-poll threads
(default 1, ``GRPC_RDMA_POLLER_THREAD_NUM``) round-robin over a slot array of
registered pairs; when a pair has a message / a resumable pending write / an error,
the poller writes that pair's wakeup fd so a selector blocked in epoll wakes
(``poller.cc:52-106``).  Threads sleep on a condvar when no pairs are registered
(``poller.cc:58-63``); capacity 4096 pairs (``poller.h:12``).

And ``PairPool`` (``pair.h:273-333``): keyed take/putback recycling of pairs — the
client keys by server URI, the server keys by peer address
(``rdma_bp_posix.cc:748-763``); ``Pair.init()`` revives recycled pairs.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from tpurpc.analysis import locks as _dbglocks
from tpurpc.analysis.locks import make_condition, make_lock
from tpurpc.core.pair import Pair, PairState
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.utils import stats as _stats
from tpurpc.utils.config import get_config
from tpurpc.utils.trace import trace_ring

# tpurpc-lens (ISSUE 8) sampling-profiler frame markers: a thread parked
# or spinning anywhere under these functions is in the poller-wait stage
_LENS_STAGES = {
    "_wait": "poller-wait",
    "wait_readable": "poller-wait",
    "wait_writable": "poller-wait",
    "_scan_edges": "poller-wait",
    "_run": "poller-wait",
}
_profiler.register_stages(__file__, _LENS_STAGES)

#: scrape-time gauge: pairs registered with live pollers (the wake/spin/
#: sleep counters themselves ride _stats.counter_inc → the obs registry)
_POLLER_PAIRS = _metrics.fleet("poller_registered_pairs",
                               lambda p: p._pair_count)

#: Adaptive-spin state machine (BPEV recast with a per-pair activity EWMA
#: instead of an unconditional busy window):
#:
#:   hit  (spin fired / events arrived within the hot window) → ewma += α(1-ewma)
#:   miss (spin expired / slow fd wake / sleep timeout)        → ewma *= β
#:   ewma < floor → the hybrid waiter SKIPS the busy window entirely and
#:   parks on its fds (the condvar/select leg, bounded by the caller's
#:   timeout and the poller's watchdog within poller_sleep_timeout_ms).
#:
#: Hot pairs therefore stay in busy-poll — every message is caught inside a
#: spin slice and drained in a batch — while idle pairs cost zero spin CPU.
#: One spin-hit pulls a decayed pair back over the floor (α=0.5 from 0.1 →
#: 0.55), so a stream that re-heats pays exactly one fd wake.
_EWMA_HIT_ALPHA = 0.5
_EWMA_MISS_BETA = 0.5
_EWMA_SPIN_FLOOR = 0.1
#: an fd wake this close behind the (skipped or missed) busy window counts
#: as "spinning would have caught it" — in multiples of busy_polling_timeout
_HOT_WAKE_MULTIPLE = 4.0

#: Per-BATCH adoption (tpurpc-hive): one poller sweep now dispatches every
#: ready pair in a burst, and the EWMA of that burst size is the fleet-wide
#: load signal. When sweeps keep finding many ready pairs at once, per-pair
#: busy windows stop paying for themselves — N spinners on ≤cores harts just
#: steal cycles from each other — so the hybrid gate suppresses spinning
#: fleet-wide above the threshold, regardless of each pair's own hot EWMA.
#: Lock-free float (CPython stores are atomic; a lost update is one sweep of
#: staleness in a smoothed signal).
_BATCH_ALPHA = 0.3
_BATCH_SPIN_SUPPRESS = 8.0
_batch_ewma = 0.0


def _note_batch(n: int) -> None:
    global _batch_ewma
    _batch_ewma += _BATCH_ALPHA * (n - _batch_ewma)


def batch_pressure() -> float:
    """EWMA of ready-pairs-per-poller-sweep — the C100K spin-suppression
    signal (also exported for the bench artifact)."""
    return _batch_ewma


def _ewma_hit(pair: Pair) -> None:
    e = getattr(pair, "activity_ewma", 1.0)
    pair.activity_ewma = e + _EWMA_HIT_ALPHA * (1.0 - e)


def _ewma_miss(pair: Pair) -> None:
    pair.activity_ewma = getattr(pair, "activity_ewma", 1.0) * _EWMA_MISS_BETA


class Poller:
    """Round-robin scanner kicking wakeup fds (the BPEV background engine)."""

    _instance: Optional["Poller"] = None
    _instance_lock = make_lock("Poller._instance_lock")

    #: lock map, checked by `python -m tpurpc.analysis` (lint rule `lock`):
    #: the pair slots, their count, and the run flag only mutate under the
    #: condition's lock (waiters key decisions off all three)
    _GUARDED_BY = {"_pairs": "_cv", "_pair_count": "_cv", "_running": "_cv",
                   "_instance": "_instance_lock",
                   "_parked_map": "_parked_mu", "_parked_sel": "_parked_mu"}

    @classmethod
    def get(cls) -> "Poller":
        """Lazy singleton, started on first use like ``Poller::Get()``
        (``poller.h:17-35``)."""
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = Poller()
                cls._instance.start()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.stop()

    def __init__(self, thread_num: Optional[int] = None):
        cfg = get_config()
        self.thread_num = thread_num or cfg.poller_thread_num
        self.capacity = cfg.poller_capacity
        self.sleep_timeout_s = cfg.poller_sleep_timeout_ms / 1000.0
        # cfg.polling_yield (the reference's fixed yield knob) is subsumed
        # by the adaptive scan cadence in _run: hot scans run at 1 ms, idle
        # streaks back off exponentially to sleep_timeout_s.
        self._pairs: List[Optional[Pair]] = []
        self._lock = make_lock("Poller._lock")
        self._cv = make_condition("Poller._cv", self._lock)
        self._threads: List[threading.Thread] = []
        self._running = False
        self._pair_count = 0
        # Parked-stub watcher (tpurpc-hive): notify sockets of parked pairs,
        # polled each sweep so an OWNERLESS parked pair (no endpoint thread
        # blocked on it) still sees the peer's WAKE/REARM frames. Lock order:
        # _cv before _parked_mu where both are held.
        self._parked_mu = make_lock("Poller._parked_mu")
        self._parked_map: Dict[int, Pair] = {}
        self._parked_sel = None  # lazy selectors.DefaultSelector
        _POLLER_PAIRS.track(self)

    # -- registration --------------------------------------------------------

    def add_pollable(self, pair: Pair) -> None:
        with self._cv:
            if self._pair_count >= self.capacity:
                raise RuntimeError(f"poller at capacity ({self.capacity} pairs)")
            for i, slot in enumerate(self._pairs):
                if slot is None:
                    self._pairs[i] = pair
                    break
            else:
                self._pairs.append(pair)
            self._pair_count += 1
            self._cv.notify_all()

    def remove_pollable(self, pair: Pair) -> bool:
        """Returns True when the pair held a slot — park remembers it so
        unpark can restore the registration."""
        with self._cv:
            for i, slot in enumerate(self._pairs):
                if slot is pair:
                    self._pairs[i] = None
                    self._pair_count -= 1
                    return True
        return False

    # -- parked-stub watcher (tpurpc-hive) -----------------------------------

    @classmethod
    def note_parked(cls, pair: Pair) -> None:
        """A pair completed its park: free its poller slot (its scan cost
        drops to zero) and watch its notify socket for the wake/re-arm
        frames that end the episode."""
        inst = cls.get()
        pair._poller_was_registered = inst.remove_pollable(pair)
        inst.add_parked(pair)

    @classmethod
    def note_unparked(cls, pair: Pair) -> None:
        inst = cls.get()
        inst.remove_parked(pair)
        if getattr(pair, "_poller_was_registered", False):
            pair._poller_was_registered = False
            try:
                inst.add_pollable(pair)
            except RuntimeError:
                # poller refilled while we were parked; waiters still wake on
                # tokens/kicks, just without the recovery scan
                _stats.counter_inc("poller_unpark_slotless")

    @classmethod
    def forget_parked(cls, pair: Pair) -> None:
        """Teardown of a parked pair: drop the watcher slot, nothing else."""
        with cls._instance_lock:
            inst = cls._instance
        if inst is not None:
            inst.remove_parked(pair)

    def add_parked(self, pair: Pair) -> None:
        import selectors

        sock = pair.notify_sock
        if sock is None:
            return
        with self._parked_mu:
            if self._parked_sel is None:
                self._parked_sel = selectors.DefaultSelector()
            try:
                fd = sock.fileno()
                self._parked_sel.register(sock, selectors.EVENT_READ, pair)
                self._parked_map[fd] = pair
            except (KeyError, ValueError, OSError):
                return  # already watched / socket racing closed
        with self._cv:
            self._cv.notify_all()  # leave the zero-pairs long sleep

    def remove_parked(self, pair: Pair) -> None:
        with self._parked_mu:
            sel = self._parked_sel
            if sel is None:
                return
            for fd in [f for f, p in self._parked_map.items() if p is pair]:
                del self._parked_map[fd]
                try:
                    sel.unregister(fd)
                except (KeyError, ValueError, OSError):
                    pass

    def parked_count(self) -> int:
        with self._parked_mu:
            return len(self._parked_map)

    def _scan_parked(self) -> bool:
        """Drain notify streams of parked stubs (one zero-timeout select over
        the whole fleet); a WAKE/REARM found here runs the unpark inline."""
        with self._parked_mu:
            sel = self._parked_sel
            if sel is None or not self._parked_map:
                return False
            try:
                events = sel.select(timeout=0)
            except (OSError, ValueError):
                events = []
            ready = [key.data for key, _ in events]
        hot = False
        for pair in ready:
            try:
                if pair.drain_notifications():
                    pair.kick()
                hot = True
                if (pair.state is not PairState.CONNECTED
                        or not (pair._parked or pair._park_pending)):
                    self.remove_parked(pair)
            except Exception:
                self.remove_parked(pair)
        return hot

    def _park_sweep(self, snapshot: List[Pair], now: float) -> None:
        """Initiate park episodes for idle registered pairs — bounded per
        sweep so a mass-idle fleet parks over a few sweeps instead of one
        stop-the-world burst of handshakes."""
        park_s = get_config().pair_park_s
        if park_s <= 0:
            return
        budget = 64
        for pair in snapshot:
            if budget <= 0:
                return
            try:
                if pair.maybe_park(now, park_s):
                    budget -= 1
            except Exception:
                pass  # dying pair; its owner observes the state

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # Flip the run flag under the scan loop's lock: an unlocked
        # `self._running = True` could race a concurrent stop() into a
        # started-but-flagged-stopped poller whose threads never exit their
        # first wait (the lock-map pass flags the unlocked mutation).
        with self._cv:
            if self._running:
                return
            self._running = True
        for i in range(self.thread_num):
            t = threading.Thread(target=self._run, name=f"tpurpc-poller-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        trace_ring.log("poller started (%d threads)", self.thread_num)

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    # -- the scan loop (poller.cc:52-106) --------------------------------------

    def _run(self) -> None:
        # Watchdog cadence, NOT a busy scan. The reference busy-spins its
        # poller on a DEDICATED core because its one-sided NIC writes carry
        # no events at all (poller.cc:52-106); tpurpc's domains deliver a
        # notify token on every send/credit-publish, and kicks are per-role-
        # pipe lossless, so waiters are woken by tokens in the common path.
        # The poller's job is recovery from pathological token loss — the
        # cadence ADAPTS (round 1 was a hot scan, ~15-25% of wall time on a
        # 1-CPU host; the round-5 fixed 1 ms heartbeat burned 1000 wakeups/s
        # on a fully idle process): scans that find work keep the 1 ms
        # cadence, idle streaks back the interval off exponentially toward
        # the condvar bound (poller_sleep_timeout_ms), which also caps the
        # token-loss recovery latency exactly as configured.
        interval = 0.001
        while True:
            with self._cv:
                if not self._running:
                    return
                with self._parked_mu:
                    n_parked = len(self._parked_map)
                if self._pair_count == 0 and n_parked == 0:
                    self._cv.wait(timeout=self.sleep_timeout_s)
                    interval = 0.001  # registrations re-arm the fast scan
                    continue
                snapshot = [p for p in self._pairs if p is not None]
            # Batched dispatch (tpurpc-hive): ONE sweep collects every pair
            # whose watched condition edged, then kicks them all in a burst —
            # the Python rendering of one epoll_wait batch fanning out N
            # wakeups, instead of N interleaved scan/kick round-trips. The
            # burst size feeds the per-batch adoption EWMA that suppresses
            # per-pair busy windows under fleet-wide pressure.
            woken: List[Pair] = []
            for pair in snapshot:
                try:
                    if self._scan_edges(pair):
                        woken.append(pair)
                except Exception:
                    # A dying pair must never take the poller down; kick so
                    # the owner observes the error state.
                    pair.kick()
            for pair in woken:
                pair.kick()
            hot = bool(woken)
            if snapshot:
                _note_batch(len(woken))
            if woken:
                _stats.batch_hist("poller_batch_wakeups").record(len(woken))
            if self._scan_parked():
                hot = True
            self._park_sweep(snapshot, time.monotonic())
            if hot:
                _stats.counter_inc("poller_scan_hot")
                interval = 0.001
            else:
                _stats.counter_inc("poller_scan_idle")
                interval = min(interval * 2, self.sleep_timeout_s)
            with self._cv:
                if not self._running:
                    return
                self._cv.wait(timeout=interval)

    @staticmethod
    def _needs_attention(pair: Pair) -> bool:
        if pair.state in (PairState.ERROR, PairState.HALF_CLOSED):
            return True
        if pair.has_message():
            return True
        if pair.has_pending_writes():
            return True
        # Non-consuming probe: notify tokens stay in the socket for whichever
        # waiter owns them; peer death still flips the pair to ERROR here.
        if pair.peek_events():
            return True
        return pair.state in (PairState.ERROR, PairState.HALF_CLOSED)

    @staticmethod
    def _scan_edges(pair: Pair) -> bool:
        """Kick only on a false→true EDGE of each watched condition.

        Kicks are lossless (unconditional per-role pipe writes), so one kick
        per condition-arrival suffices: a waiter only ever blocks after
        observing its predicate false, which can only happen after the
        condition cleared — the next arrival is a fresh edge and a fresh
        kick. Level-triggered re-kicking (round 1) kept the scan loop and
        both wakeup pipes hot for the entire lifetime of every in-flight
        message.
        """
        state = (pair.has_message(), pair.has_pending_writes(),
                 pair.state in (PairState.ERROR, PairState.HALF_CLOSED)
                 or pair.peek_events())
        prev = getattr(pair, "_poller_edges", (False, False, False))
        pair._poller_edges = state
        return any(now and not was for now, was in zip(state, prev))


def wait_readable(pair: Pair, timeout: Optional[float] = None,
                  discipline: Optional[str] = None) -> bool:
    """Block until ``pair`` has something for its owner (message, resumable write,
    state change) under one of the three wakeup disciplines — the ``pollable_epoll``
    seam of the reference condensed to one function:

    * ``"busy"``  — pure spin until deadline (``ev_epollex_rdma_bp_linux.cc:1020-1110``)
    * ``"event"`` — block on the peer-driven notify socket
      (``ev_epollex_rdma_event_linux.cc:686-706``, completion-channel fds in epoll)
    * ``"hybrid"``— spin ≤ ``busy_polling_timeout_us`` then block on the notify socket
      *and* the poller-written wakeup fd (``ev_epollex_rdma_bpev_linux.cc:1079-1160``);
      requires the pair to be registered with :class:`Poller`.

    Returns True if the pair needs attention, False on timeout.
    """
    return _wait(pair, timeout, discipline,
                 lambda: (pair.has_message() or pair.has_pending_writes()
                          or pair.state not in (PairState.CONNECTED,)),
                 role="read")


def wait_writable(pair: Pair, timeout: Optional[float] = None,
                  discipline: Optional[str] = None) -> bool:
    """Block until a credit-stalled write can resume (or the pair dies).

    Distinct from :func:`wait_readable` on purpose: a writer stalled for credits
    must NOT be woken by unread *inbound* data (``has_message``), or a
    request-response app that writes before reading would busy-spin through its
    stall loop at 100% CPU.
    """
    return _wait(pair, timeout, discipline,
                 lambda: (pair.has_pending_writes()
                          or pair.state not in (PairState.CONNECTED,)),
                 role="write")


_CPUS: Optional[int] = None


def _effective_cpus() -> int:
    global _CPUS
    if _CPUS is None:
        import os

        try:
            _CPUS = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            _CPUS = os.cpu_count() or 1
    return _CPUS


def _wait(pair: Pair, timeout: Optional[float], discipline: Optional[str],
          predicate, role: str = "read") -> bool:
    cfg = get_config()
    if discipline is None:
        discipline = cfg.platform.discipline or "hybrid"

    def ready() -> bool:
        if pair.drain_notifications():
            # We may have consumed a token another waiter (full-duplex: the
            # write side of the same endpoint) was blocked on — kick the
            # OTHER role's pipe so it re-checks (each role consumes only its
            # own pipe, so the broadcast cannot be stolen; our own predicate
            # is checked right below, no self-kick needed).
            pair.kick(exclude=role)
        return predicate()

    deadline = None if timeout is None else time.monotonic() + timeout
    if ready():
        return True

    #: one native spin slice; full pair state (error/exit words, notify-channel
    #: death) is re-checked in Python between slices.
    _SLICE_US = 500

    # Hybrid's busy window presumes a core to burn while ANOTHER core
    # produces (the reference pins dedicated poller threads, poller.cc:52).
    # On a single-hart host every spin microsecond is stolen from the
    # producer, so hybrid degrades to pure event; explicit "busy" is honored
    # as configured. (A cooperative sleep(0)-yield spin was tried here in
    # round 4 and MEASURED WORSE — wait p50 274→376µs — because with the
    # server's reader+worker threads also runnable, the yielding spinner
    # still consumes every other scheduler slot the handler needed. The
    # Python-path latency answer is the native unary fast path in
    # rpc/channel.py, not a smarter spin.)
    if discipline == "hybrid" and _effective_cpus() < 2:
        discipline = "event"

    if discipline in ("busy", "hybrid"):
        # Adaptive gate (hybrid only): a pair whose activity EWMA decayed
        # below the floor — spins haven't paid off lately — skips the busy
        # window and parks on its fds immediately. "busy" is explicit
        # operator intent and always spins. Mode FLIPS (BP↔EV adoption)
        # are flight-recorder events: rare edges, and exactly the record a
        # wake-latency postmortem needs (tpurpc-blackbox, ISSUE 5).
        ewma = getattr(pair, "activity_ewma", 1.0)
        suppressed = False
        if (discipline == "hybrid"
                and batch_pressure() >= _BATCH_SPIN_SUPPRESS):
            # fleet-wide pressure: sweeps keep finding many ready pairs at
            # once, so per-pair spinners only steal each other's cycles —
            # adopt the event leg regardless of this pair's own hot EWMA
            suppressed = True
            _stats.counter_inc("wait_spin_suppressed_batch")
        if discipline == "hybrid" and (suppressed
                                       or ewma < _EWMA_SPIN_FLOOR):
            _stats.counter_inc("wait_spin_skipped")
            if getattr(pair, "_flight_mode", "bp") != "ev":
                pair._flight_mode = "ev"
                ftag = getattr(pair, "_ftag", 0)
                _flight.emit(_flight.POLLER_EV, ftag)
        else:
            if (discipline == "hybrid"
                    and getattr(pair, "_flight_mode", "bp") != "bp"):
                pair._flight_mode = "bp"
                ftag = getattr(pair, "_ftag", 0)
                _flight.emit(_flight.POLLER_BP, ftag)
            if discipline == "busy":
                spin_deadline = (deadline if deadline is not None
                                 else float("inf"))
            else:
                spin_deadline = (time.monotonic()
                                 + cfg.busy_polling_timeout_us / 1e6)
            while True:
                now = time.monotonic()
                if now >= spin_deadline:
                    break
                slice_us = _SLICE_US
                if spin_deadline != float("inf"):
                    slice_us = max(1, min(_SLICE_US,
                                          int((spin_deadline - now) * 1e6)))
                # GIL-free native spin on the watched words; True = fired (or
                # spin unavailable — then this degrades to a pure Python poll
                # loop).
                pair.spin(role, slice_us)
                if ready():
                    _ewma_hit(pair)
                    _stats.counter_inc("wait_spin_hit")
                    return True
            _ewma_miss(pair)
            _stats.counter_inc("wait_spin_miss")
        if discipline == "busy":
            return ready()

    # Block on fds (event + hybrid): the shared notify socket (peer-driven
    # tokens) and this role's OWN wakeup pipe (poller kicks + cross-waiter
    # broadcast). No cap on the select: every state transition is followed by
    # a token (peer) or a kick (poller / token-drainer), and the per-role pipe
    # means no other thread can consume our wakeup between our predicate check
    # and the select — the race the old 50 ms cap papered over. The selector
    # is persistent per (pair, role): rebuilding epoll state every wait is 5
    # syscalls of overhead per small RPC.
    sel = pair.waiter_selector(role)
    if not sel.get_map():
        # nothing registerable — the pair's channels are (being) released;
        # never block on an empty selector
        return ready()
    # Advertise "blocked on the notify fd" for the whole sleeping phase, so
    # producers pay the notify syscall only while someone is actually asleep
    # (futex-style handshake; fences + lost-wakeup proof in ring.cc). Order
    # matters: flag up (full fence) BEFORE the predicate re-check before each
    # select — a producer that missed the flag must be visible to the
    # re-check, and one that saw it sends the byte the select consumes.
    pair.set_waiting(role, True)
    if _dbglocks.ENABLED:
        _dbglocks.note_blocking("waiter selector.select "
                                f"({role}, pair {pair.tag})")
    _stats.counter_inc("wait_sleep")
    sleep_t0 = time.monotonic()
    #: a wake this fast after parking means a busy window would have caught
    #: the event — count it toward re-arming the adaptive spin
    hot_window_s = _HOT_WAKE_MULTIPLE * cfg.busy_polling_timeout_us / 1e6
    try:
        while True:
            if ready():
                if time.monotonic() - sleep_t0 <= hot_window_s:
                    _ewma_hit(pair)
                else:
                    _ewma_miss(pair)
                return True
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return ready()
            try:
                events = sel.select(timeout=remain)
            except (OSError, ValueError):
                # A racing local close() invalidated a registered fd — that IS
                # a state change; surface it through the predicate.
                return ready()
            if events:
                pair.consume_wakeup(role)
                # loop back to the top, where ready() re-checks the predicate
    finally:
        pair.set_waiting(role, False)


class PairPool:
    """Keyed pair recycling (``pair.h:273-333``).  Pairs are returned under the peer
    key and revived by ``init()`` on the next take.  What's recycled is the Pair
    *object* and its domain binding; ring regions are allocated fresh per
    connection (see ``Pair.init`` for why stale one-sided writes forbid reuse)."""

    _instance: Optional["PairPool"] = None
    _instance_lock = make_lock("PairPool._instance_lock")

    #: lock map, checked by `python -m tpurpc.analysis` (lint rule `lock`)
    _GUARDED_BY = {"_idle": "_lock", "_idle_total": "_lock",
                   "_instance": "_instance_lock"}

    @classmethod
    def get(cls) -> "PairPool":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = PairPool()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.drain()

    def __init__(self, pair_factory: Optional[Callable[[], Pair]] = None,
                 max_idle_total: Optional[int] = None,
                 max_idle_per_key: Optional[int] = None):
        cfg = get_config()
        if pair_factory is None:
            # Domain per config (TPURPC_RING_DOMAIN): shm by default (works
            # in-process and cross-process on one host); tcp_window carries
            # the same protocol across hosts (tpurpc/core/tcpw.py). Read at
            # CALL time — the pool is a process singleton that outlives a
            # config reload, and take() validates recycled pairs against
            # the current domain the same way.
            from tpurpc.core.pair import make_domain

            pair_factory = lambda: Pair(  # noqa: E731
                make_domain(get_config().ring_domain))
        self.pair_factory = pair_factory
        #: global bound = the reference's flat 128-pair pool (pair.h:273);
        #: the per-key default is a QUARTER of it so one hot peer key cannot
        #: evict-starve every other key (r1 verdict: equal bounds did). An
        #: explicit max_idle_per_key is honored as given.
        self.max_idle_total = (max_idle_total if max_idle_total is not None
                               else cfg.pair_pool_size)
        self.max_idle_per_key = (max_idle_per_key
                                 if max_idle_per_key is not None
                                 else max(1, self.max_idle_total // 4))
        self._idle: Dict[str, List[Pair]] = defaultdict(list)
        self._idle_total = 0
        self._lock = make_lock("PairPool._lock")

    def take(self, key: str) -> Pair:
        from tpurpc.utils.config import get_config as _gc

        want_domain = _gc().ring_domain
        stale: List[Pair] = []
        with self._lock:
            bucket = self._idle.get(key)
            pair = None
            while bucket:
                cand = bucket.pop()
                self._idle_total -= 1
                # A pair is BOUND to its memory domain; recycling one
                # across a TPURPC_RING_DOMAIN change would advertise the
                # old domain at bootstrap and fail the handshake with a
                # domain-mismatch (observed: a tcp_window-era pooled pair
                # reused after the config flipped back to shm).
                if getattr(cand.domain, "kind", want_domain) == want_domain:
                    pair = cand
                    break
                stale.append(cand)
        for cand in stale:
            cand.destroy()
        if pair is None:
            pair = self.pair_factory()
        pair.init()
        return pair

    def putback(self, key: str, pair: Pair) -> None:
        """Quiesce (drop fds + peer refs, keep ring allocations) and shelve.  Pairs
        beyond the global bound are destroyed outright."""
        pair.quiesce()
        with self._lock:
            bucket = self._idle[key]
            if (len(bucket) < self.max_idle_per_key
                    and self._idle_total < self.max_idle_total):
                bucket.append(pair)
                self._idle_total += 1
                return
        pair.destroy()

    def idle_count(self, key: str) -> int:
        with self._lock:
            return len(self._idle.get(key, []))

    def drain(self) -> None:
        """Destroy every idle pair (releases ring memory, incl. /dev/shm files)."""
        with self._lock:
            pairs = [p for bucket in self._idle.values() for p in bucket]
            self._idle.clear()
            self._idle_total = 0
        for p in pairs:
            p.destroy()
