"""Endpoint: the swappable byte-pipe seam, and the factory that swaps it.

This is the single most important architectural idea taken from the reference
(SURVEY.md §1): *one stable endpoint interface with N byte-pipes selected at runtime by
an env var, chosen at connection-accept time, with everything above it untouched*.

Reference mapping:

* ``Endpoint`` ≈ the 12-slot ``grpc_endpoint`` vtable (``src/core/lib/iomgr/
  endpoint.h`` — read/write/shutdown/destroy/get_peer/get_local_address/get_fd/
  can_track_err; the pollset slots collapse into our blocking-with-timeout model).
* ``create_endpoint`` ≈ the fork's new factory ``grpc_endpoint_create(fd, args, peer,
  is_server)`` (``endpoint.cc:33-54``), called from the accept loop
  (``tcp_server_posix.cc:267``) and the client connector
  (``tcp_client_posix.cc:124-126``).
* ``RingEndpoint`` ≈ ``grpc_rdma`` wrapping a ``PairPollable``
  (``rdma_bp_posix.cc:45-82``): creation takes a pooled pair, bootstraps it over the
  just-connected socket, and (hybrid mode) registers it with the background poller
  (``:706-796``); teardown removes it from the poller, disconnects, and returns the
  pair to the pool (``:112-132``).  Read surfaces ``HALF_CLOSED``-after-drain as EOF
  and ``ERROR`` as ``ConnectionError`` — the UNAVAILABLE-and-reconnect contract
  (``rdma_bp_posix.cc:86-96``).
* ``MockEndpoint`` / ``PassthruEndpoint`` ≈ ``test/core/util/mock_endpoint.cc`` and
  ``passthru_endpoint.cc`` — the scriptable seams the upstream test suite (and ours)
  builds on.

Thread model: tpurpc uses blocking endpoints driven by a thread per connection instead
of porting iomgr's closure/combiner machinery — idiomatic for Python, and the native
C++ core owns the genuinely hot loops.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Callable, Optional, Tuple

from tpurpc.core.pair import Pair, PairState
from tpurpc.core.poller import PairPool, Poller, wait_readable, wait_writable
from tpurpc.obs import flight as _flight
from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.utils.config import Platform, get_config
from tpurpc.utils.trace import trace_endpoint

# tpurpc-lens (ISSUE 8): on the framed (h2-over-TCP) plane the `wire`
# waterfall hop is the socket write — gather sendmsg / sendall / the TLS
# chunk loop. (The pair plane's wire hop is Pair.send in core/pair.py.)
_LENS_WIRE_BYTES, _LENS_WIRE_NS, _LENS_WIRE_COPY = _lens.hop_counters("wire")

_LENS_STAGES = {
    "write": "wire",
    "_ssl_send_all": "wire",
    "read": "wire",
    "read_into": "wire",
    "_await_readable": "poller-wait",
}
_profiler.register_stages(__file__, _LENS_STAGES)


class EndpointError(ConnectionError):
    """Transport-level failure; RPC layer maps it to UNAVAILABLE (ref:
    ``rdma_bp_posix.cc:86-96`` annotating endpoint errors with
    ``GRPC_STATUS_UNAVAILABLE`` so client_channel reconnects)."""


class Endpoint:
    """Blocking byte-pipe with the grpc_endpoint contract.

    * ``read`` returns ≥1 byte, or ``b""`` exactly once at clean EOF, or raises
      :class:`EndpointError`.
    * ``write`` accepts the whole buffer or raises.
    * ``close`` is idempotent and releases transport resources.
    """

    def read(self, max_bytes: int = 1 << 20,
             timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def read_into(self, dst, timeout: Optional[float] = None) -> int:
        """Read ≥1 byte directly into ``dst``; 0 exactly once at clean EOF.

        Default shim bounces through :meth:`read`; transports with placement
        control (TCP ``recv_into``, ring drain) override to skip the copy.
        """
        dst = memoryview(dst).cast("B")
        data = self.read(len(dst), timeout=timeout)
        n = len(data)
        dst[:n] = data
        return n

    def write(self, data) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def peer(self) -> str:
        raise NotImplementedError

    @property
    def local_address(self) -> str:
        raise NotImplementedError

    def fileno(self) -> int:
        """fd for pollers where one exists; -1 otherwise (``grpc_endpoint_get_fd``)."""
        return -1

    def can_track_err(self) -> bool:
        return False

    def peer_cert(self) -> "Optional[dict]":
        """The peer's TLS certificate (``SSLSocket.getpeercert()`` dict),
        None on non-TLS transports. Each transport serializes the probe
        with whatever lock guards its SSL object — OpenSSL forbids
        concurrent use of one SSL*."""
        return None


class ReadTimeout(TimeoutError):
    pass


# ---------------------------------------------------------------------------
# TCP endpoint (ref: tcp_posix.cc — the fallback pipe and the bootstrap carrier).
# ---------------------------------------------------------------------------

class TcpEndpoint(Endpoint):
    def __init__(self, sock: socket.socket, preread: bytes = b""):
        self._sock = sock
        #: bytes already consumed from the socket by the listener's protocol
        #: peek (ring-platform dispatch); served to readers first
        self._preread = bytearray(preread)
        #: TLS only: serializes ALL OpenSSL calls on this socket. CPython
        #: releases the GIL around SSL_read/SSL_write, and OpenSSL forbids
        #: concurrent use of one SSL* — the reader and writer threads racing
        #: produced sporadic DECRYPTION_FAILED_OR_BAD_RECORD_MAC under load
        #: (the round-2/3 mTLS flake). Lock holds are bounded (every locked
        #: SSL call carries a short settimeout); fd-level readiness waits
        #: happen OUTSIDE the lock, so a blocked peer can never deadlock the
        #: two directions against each other.
        self._ssl_lock = (threading.Lock()
                          if hasattr(sock, "pending") else None)
        # (peer_cert below shares _ssl_lock for the same reason.)
        # The socket stays BLOCKING for its whole life; read deadlines are a
        # select() ahead of the recv instead of settimeout(). settimeout is
        # per-socket state, so a writer thread flipping it to blocking would
        # clobber a concurrent reader's deadline (last-setter-wins) — the
        # FrameReader's resume path depends on its ReadTimeout actually
        # firing. (TLS sockets DO flip settimeout, but only under _ssl_lock,
        # which every SSL read and write holds — race-free by construction.)
        sock.setblocking(True)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets
        self._peer = _fmt_addr(sock, peer=True)
        self._local = _fmt_addr(sock, peer=False)
        self._closed = False

    def _await_readable(self, timeout: Optional[float]) -> None:
        # Plaintext sockets only: TLS reads divert to _ssl_recv before
        # reaching here (whose locked-recv-first pass covers the
        # TLS-buffered-plaintext case poll() can't see).
        if timeout is None:
            return
        import select

        # poll(), not select(): select.select fails outright for fds >=
        # FD_SETSIZE (1024), which a busy server crosses easily.
        try:
            p = select.poll()
            p.register(self._sock.fileno(), select.POLLIN)
            r = p.poll(max(0.0, timeout) * 1000.0)
        except (OSError, ValueError) as exc:
            raise EndpointError(f"tcp read failed: {exc}") from exc
        if not r:
            raise ReadTimeout()

    def _ssl_recv(self, fn, timeout: Optional[float]):
        """One serialized SSL read. Each pass tries a short locked SSL_read
        first (TLS-buffered plaintext is invisible to the raw fd, and
        SSL_pending itself isn't safe to probe unlocked), then waits for
        raw-fd readability OUTSIDE the lock — so an idle reader parks in
        poll() holding nothing and a writer is never starved."""
        import select
        import ssl as _ssl

        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while True:
            hold = 0.1
            if deadline is not None:
                # honor sub-100ms deadlines: never block past the caller's
                # budget inside the locked recv
                hold = max(0.001, min(hold, deadline - time.monotonic()))
            with self._ssl_lock:
                if self._closed:
                    raise EndpointError("read on closed endpoint")
                self._sock.settimeout(hold)
                try:
                    return fn()
                except (socket.timeout, _ssl.SSLWantReadError):
                    pass  # nothing buffered/partial record: wait off-lock
                finally:
                    try:
                        self._sock.settimeout(None)
                    except OSError:
                        pass
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise ReadTimeout()
                slice_s = min(remain, 5.0)
            else:
                slice_s = 5.0
            try:
                p = select.poll()
                p.register(self._sock.fileno(), select.POLLIN)
                p.poll(slice_s * 1000.0)
            except (OSError, ValueError) as exc:
                raise EndpointError(f"tcp read failed: {exc}") from exc

    def read(self, max_bytes: int = 1 << 20,
             timeout: Optional[float] = None) -> bytes:
        if self._closed:
            raise EndpointError("read on closed endpoint")
        if self._preread:
            out = bytes(self._preread[:max_bytes])
            del self._preread[:max_bytes]
            return out
        try:
            if self._ssl_lock is not None:
                return self._ssl_recv(lambda: self._sock.recv(max_bytes),
                                      timeout)
            self._await_readable(timeout)
            return self._sock.recv(max_bytes)
        except socket.timeout as exc:
            raise ReadTimeout() from exc
        except OSError as exc:
            raise EndpointError(f"tcp read failed: {exc}") from exc

    def read_into(self, dst, timeout: Optional[float] = None) -> int:
        if self._closed:
            raise EndpointError("read on closed endpoint")
        if self._preread:
            dst = memoryview(dst).cast("B")
            n = min(len(dst), len(self._preread))
            dst[:n] = self._preread[:n]
            del self._preread[:n]
            return n
        try:
            if self._ssl_lock is not None:
                return self._ssl_recv(lambda: self._sock.recv_into(dst),
                                      timeout)
            self._await_readable(timeout)
            return self._sock.recv_into(dst)
        except socket.timeout as exc:
            raise ReadTimeout() from exc
        except OSError as exc:
            raise EndpointError(f"tcp read failed: {exc}") from exc

    def _ssl_send_all(self, data: bytes) -> None:
        """Serialized SSL write in bounded-lock chunks. On a timed-out
        chunk the SSL layer demands a retry with the SAME buffer (no
        partial-write mode) — the loop re-sends the identical view, and the
        released lock between attempts lets the reader drain (which is what
        un-wedges a peer blocked on its own full send buffer)."""
        import select
        import ssl as _ssl

        view = memoryview(data).cast("B")
        pos = 0
        while pos < len(view):
            with self._ssl_lock:
                if self._closed:
                    raise EndpointError("write on closed endpoint")
                self._sock.settimeout(0.2)
                try:
                    budget = time.monotonic() + 0.2  # bound the lock hold
                    while pos < len(view) and time.monotonic() < budget:
                        # single send() per step: a timed-out SSL_write is
                        # pending inside OpenSSL and MUST be retried with
                        # the buffer at the SAME position — pos advances
                        # only on success, so the retry resends view[pos:]
                        # exactly (sendall would restart the prefix and
                        # corrupt the record stream)
                        pos += self._sock.send(view[pos:pos + 65536])
                except (socket.timeout, _ssl.SSLWantWriteError):
                    pass  # retry same position after the peer drains
                finally:
                    try:
                        self._sock.settimeout(None)
                    except OSError:
                        pass
            if pos >= len(view):
                break
            # off-lock: wait for room so the retry isn't a hot spin (and the
            # reader can take the lock meanwhile — the anti-deadlock step)
            try:
                p = select.poll()
                p.register(self._sock.fileno(), select.POLLOUT)
                p.poll(200)
            except (OSError, ValueError):
                pass  # racing close: the locked retry will surface it

    def write(self, data) -> None:
        if self._closed:
            raise EndpointError("write on closed endpoint")
        t0 = time.monotonic_ns()
        total = 0
        try:
            if self._ssl_lock is not None:
                # SSLSocket (sendmsg raises NotImplementedError there):
                # records are re-framed anyway, so ONE join costs what the
                # TLS layer would have paid internally (bytes.join accepts
                # memoryviews directly; scalars pass through zero-copy —
                # _ssl_send_all wraps them in a memoryview itself).
                blob = (b"".join(data) if isinstance(data, (list, tuple))
                        else data)
                total = len(blob)
                self._ssl_send_all(blob)
                return
            if isinstance(data, (list, tuple)):
                # sendmsg is a gather write but may place PARTIALLY under
                # pressure, and the kernel caps one call at IOV_MAX=1024
                # iovecs (a large pytree serializes to 2-3 segments per leaf);
                # loop chunked until every byte is on the wire.
                views = [memoryview(s).cast("B") for s in data if len(s)]
                total = sum(len(v) for v in views)
                while views:
                    sent = self._sock.sendmsg(views[:1024])
                    while sent:
                        if sent >= len(views[0]):
                            sent -= len(views[0])
                            views.pop(0)
                        else:
                            views[0] = views[0][sent:]
                            sent = 0
            else:
                total = len(memoryview(data).cast("B"))
                self._sock.sendall(data)
        except OSError as exc:
            raise EndpointError(f"tcp write failed: {exc}") from exc
        finally:
            # tpurpc-lens `wire` hop: socket bytes moved + the nanoseconds
            # the kernel handoff took (backpressure blocking included — a
            # full socket buffer IS wire time). One bump set per writev.
            dt = time.monotonic_ns() - t0
            _LENS_WIRE_NS.inc(dt)
            _LENS_WIRE_BYTES.inc(total)
            _LENS_WIRE_COPY.inc(total)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()

    @property
    def peer(self) -> str:
        return self._peer

    @property
    def local_address(self) -> str:
        return self._local

    def fileno(self) -> int:
        return -1 if self._closed else self._sock.fileno()

    def can_track_err(self) -> bool:
        return True

    def peer_cert(self) -> "Optional[dict]":
        sock = self._sock
        if self._ssl_lock is None or not hasattr(sock, "getpeercert"):
            return None  # plaintext
        try:
            with self._ssl_lock:  # ALL OpenSSL calls on one SSL* serialize
                return sock.getpeercert()
        except (OSError, ValueError):
            return None


def device_ring_of(endpoint: Endpoint):
    """The endpoint's device (HBM) receive ring, or None off-platform.

    Single probe shared by the server and client surfaces: present only on
    :class:`tpurpc.tpu.endpoint.TpuRingEndpoint` (``GRPC_PLATFORM_TYPE=TPU``).
    Checks the class attribute first so non-TPU endpoints pay no lazy-init."""
    if isinstance(getattr(type(endpoint), "device_ring", None), property):
        return endpoint.device_ring
    return None


def _fmt_addr(sock: socket.socket, peer: bool) -> str:
    try:
        addr = sock.getpeername() if peer else sock.getsockname()
    except OSError:
        return "unknown:?"
    if isinstance(addr, tuple):
        return f"ipv4:{addr[0]}:{addr[1]}" if len(addr) == 2 else f"ipv6:[{addr[0]}]:{addr[1]}"
    return f"unix:{addr or '(unnamed)'}"


# ---------------------------------------------------------------------------
# Ring endpoint (ref: rdma_bp_posix.cc / rdma_event_posix.cc).
# ---------------------------------------------------------------------------

class RingEndpoint(Endpoint):
    """A pooled Pair fronted by the Endpoint contract.

    ``sendmsg``-style gather writes map to the pair's slice-gather send; reads drain
    the ring and surface close/error per the reference's contract.
    """

    def __init__(self, sock: socket.socket, *, discipline: str,
                 pool_key: str, pair: Optional[Pair] = None,
                 register_with_poller: Optional[bool] = None,
                 preread: bytes = b""):
        self.discipline = discipline
        self.pool_key = pool_key
        self._peer_desc = _fmt_addr(sock, peer=True)
        self._local_desc = _fmt_addr(sock, peer=False)
        self.pair = pair if pair is not None else PairPool.get().take(pool_key)
        if self.pair.state is not PairState.CONNECTED:
            try:
                self.pair.connect_over_socket(sock, preread=preread)
            except Exception:
                # Failed bootstrap (e.g. platform-mismatched peer): release the
                # rings now, don't leak them until interpreter exit.
                self.pair.destroy()
                raise
        self._registered = (register_with_poller if register_with_poller is not None
                            else discipline == "hybrid")
        if self._registered:
            Poller.get().add_pollable(self.pair)
        self._closed = False
        #: in-flight read/write tracking: close() must NOT return the pair to
        #: the pool while a (possibly blocked) reader/writer thread is still
        #: inside it — the pool would hand the same Pair to a NEW connection
        #: whose reader then collides with the stale one (ContentAssertion
        #: "concurrent entry", found by the chaos churn test).
        self._ops_lock = threading.Lock()
        self._ops = 0
        self._ops_idle = threading.Event()
        self._ops_idle.set()
        trace_endpoint.log("ring endpoint up: %s <-> %s (%s)", self._local_desc,
                           self._peer_desc, discipline)

    def _op_enter(self) -> None:
        with self._ops_lock:
            if self._closed:
                raise EndpointError("endpoint closed")
            self._ops += 1
            self._ops_idle.clear()

    def _op_exit(self) -> None:
        with self._ops_lock:
            self._ops -= 1
            if self._ops == 0:
                self._ops_idle.set()

    def read(self, max_bytes: int = 1 << 20,
             timeout: Optional[float] = None) -> bytes:
        from tpurpc.core.ring import truncate_after_read

        buf = bytearray(min(max_bytes, self.pair.ring_size))
        n = self.read_into(buf, timeout=timeout)
        truncate_after_read(buf, n)
        return bytes(buf)

    def read_into(self, dst, timeout: Optional[float] = None) -> int:
        self._op_enter()
        try:
            return self._read_into_locked(dst, timeout)
        finally:
            self._op_exit()

    def _read_into_locked(self, dst, timeout: Optional[float]) -> int:
        dst = memoryview(dst).cast("B")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                n = self.pair.recv_into(dst)
            except ConnectionError as exc:
                raise EndpointError(str(exc)) from exc
            if n:
                return n
            if self._closed:
                raise EndpointError("endpoint closed")
            state = self.pair.get_status()
            if state is PairState.HALF_CLOSED:
                # The peer's final write and its peer_exit flag race: re-drain once
                # after observing HALF_CLOSED so in-flight bytes are never dropped.
                try:
                    return self.pair.recv_into(dst)
                except ConnectionError:
                    return 0
            if state in (PairState.ERROR, PairState.DISCONNECTED):
                raise EndpointError(
                    f"ring endpoint unavailable: {state.value}"
                    + (f" ({self.pair.error})" if self.pair.error else ""))
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                raise ReadTimeout()
            wait_readable(self.pair, timeout=remain, discipline=self.discipline)

    def write(self, data) -> None:
        self._op_enter()
        try:
            slices = list(data) if isinstance(data, (list, tuple)) else [data]
            total = sum(len(s) for s in slices)
            sent = 0
            while sent < total:
                try:
                    sent += self.pair.send(slices, byte_idx=sent)
                except BrokenPipeError as exc:
                    raise EndpointError(str(exc)) from exc
                if sent < total:
                    if self._closed:
                        raise EndpointError("endpoint closed")
                    # stalled for credits; wait for the peer to drain
                    wait_writable(self.pair, timeout=30,
                                  discipline=self.discipline)
                    if self.pair.get_status() not in (PairState.CONNECTED,):
                        raise EndpointError(
                            f"peer went away mid-write ({self.pair.state.value})")
        finally:
            self._op_exit()

    def close(self) -> None:
        """Teardown order per ``rdma_bp_posix.cc:112-132``: out of the poller,
        disconnect, back to the pool — with a DRAIN between disconnect and
        putback: the state change + kick wakes any thread blocked inside the
        pair, and only when every in-flight read/write has exited may the
        pool re-issue it (else a recycled pair's new owner collides with the
        stale thread — chaos-test finding)."""
        with self._ops_lock:
            if self._closed:
                return
            self._closed = True
        if self._registered:
            Poller.get().remove_pollable(self.pair)
        self.pair.disconnect()
        self.pair.kick()  # wake blocked waiters; they observe DISCONNECTED
        if not self._ops_idle.wait(timeout=10):
            # A reader is wedged past every wake path: destroying leaks this
            # pair object but NEVER hands a contended pair to a new owner.
            trace_endpoint.log("ring endpoint close: in-flight op did not "
                               "drain; destroying pair %s", self.pair.tag)
            try:
                self.pair.destroy()
            except Exception:
                # the wedged op may pin ring exports past destroy's retry
                # budget; best-effort — the one certainty close() must keep
                # is that this pair never reaches the pool
                pass
            return
        PairPool.get().putback(self.pool_key, self.pair)

    @property
    def peer(self) -> str:
        return self._peer_desc

    @property
    def local_address(self) -> str:
        return self._local_desc

    def fileno(self) -> int:
        return self.pair.wakeup_fd if not self._closed else -1

    def peer_cert(self) -> "Optional[dict]":
        # Ring platforms keep the (possibly TLS) bootstrap socket as the
        # pair's notify channel; its SSL object is serialized by the
        # pair's notify lock.
        pair = self.pair
        sock = getattr(pair, "notify_sock", None)
        if sock is None or not hasattr(sock, "getpeercert"):
            return None
        try:
            with pair._notify_lock:
                return sock.getpeercert()
        except (OSError, ValueError):
            return None


# ---------------------------------------------------------------------------
# Test endpoints (ref: test/core/util/{mock,passthru}_endpoint.cc).
# ---------------------------------------------------------------------------

class _QueueReadEndpoint(Endpoint):
    """Shared read machinery for queue-fed test endpoints: pending-tail buffering
    for reads larger than ``max_bytes``, sticky EOF on an injected ``b""``."""

    def __init__(self, rx: "queue.Queue[bytes]"):
        self._rx = rx
        self._pending = bytearray()
        self._closed = False
        self._eof = False

    def read(self, max_bytes: int = 1 << 20,
             timeout: Optional[float] = None) -> bytes:
        if self._closed:
            raise EndpointError("read on closed endpoint")
        if self._pending:
            out = bytes(self._pending[:max_bytes])
            del self._pending[:max_bytes]
            return out
        if self._eof:
            return b""
        try:
            data = self._rx.get(timeout=timeout)
        except queue.Empty:
            raise ReadTimeout() from None
        if data == b"":
            self._eof = True
        self._pending += data[max_bytes:]
        return data[:max_bytes]

    def close(self) -> None:
        self._closed = True


class MockEndpoint(_QueueReadEndpoint):
    """Scriptable endpoint: the test injects reads and captures writes."""

    def __init__(self, peer: str = "mock:peer"):
        super().__init__(queue.Queue())
        self.written = bytearray()
        self._peer_name = peer

    def inject(self, data: bytes) -> None:
        self._rx.put(data)

    def inject_eof(self) -> None:
        self._rx.put(b"")

    def write(self, data) -> None:
        if self._closed:
            raise EndpointError("write on closed endpoint")
        slices = data if isinstance(data, (list, tuple)) else [data]
        for s in slices:
            self.written += bytes(s)

    @property
    def peer(self) -> str:
        return self._peer_name

    @property
    def local_address(self) -> str:
        return "mock:local"


def passthru_endpoint_pair() -> Tuple[Endpoint, Endpoint]:
    """Two endpoints joined by in-memory queues (``passthru_endpoint.cc``)."""

    class _Half(_QueueReadEndpoint):
        def __init__(self, rx: queue.Queue, tx: queue.Queue, name: str):
            super().__init__(rx)
            self._tx, self._name = tx, name

        def write(self, data) -> None:
            if self._closed:
                raise EndpointError("write on closed endpoint")
            slices = data if isinstance(data, (list, tuple)) else [data]
            payload = b"".join(bytes(s) for s in slices)
            if payload:
                self._tx.put(payload)

        def close(self) -> None:
            if not self._closed:
                super().close()
                self._tx.put(b"")

        @property
        def peer(self) -> str:
            return f"passthru:{self._name}:peer"

        @property
        def local_address(self) -> str:
            return f"passthru:{self._name}:local"

    q1: queue.Queue = queue.Queue()
    q2: queue.Queue = queue.Queue()
    return _Half(q1, q2, "a"), _Half(q2, q1, "b")


# ---------------------------------------------------------------------------
# The factory + connectors (ref: endpoint.cc:33-54, tcp_client/server_posix.cc).
# ---------------------------------------------------------------------------

def create_endpoint(sock: socket.socket, *, is_server: bool,
                    pool_key: Optional[str] = None,
                    platform: Optional[Platform] = None) -> Endpoint:
    """Wrap a just-connected socket in the platform-selected byte pipe.

    Mirrors ``grpc_endpoint_create`` dispatch (``endpoint.cc:33-54``): TCP wraps the
    socket directly; ring platforms bootstrap a pooled pair over the socket.  The
    pool key mirrors the reference's identity rule (``rdma_bp_posix.cc:748-763``):
    clients key by the server address, servers key by the peer address.
    """
    cfg = get_config()
    platform = platform or cfg.platform
    if platform is Platform.TCP:
        return TcpEndpoint(sock)
    preread = b""
    if is_server:
        # Ring-platform listeners serve MIXED clients: ring peers open with
        # the TRB1 bootstrap magic; stock gRPC (h2 preface) and native-TCP-
        # framing clients fall through to a TCP endpoint carrying the peeked
        # bytes. An explicit 4-byte read (not MSG_PEEK) so the dispatch works
        # identically on TLS sockets, where only decrypted bytes mean
        # anything. The reference cannot do this — a vanilla gRPC client
        # cannot talk to its RDMA ports at all.
        from tpurpc.core.pair import _BOOTSTRAP_MAGIC, peek_protocol

        preread = peek_protocol(sock)
        if preread != _BOOTSTRAP_MAGIC:
            return TcpEndpoint(sock, preread=preread)
    if platform is Platform.TPU:
        from tpurpc.tpu.endpoint import TpuRingEndpoint  # lazy: jax import

        key = pool_key or _fmt_addr(sock, peer=True)
        return TpuRingEndpoint(sock, pool_key=key, is_server=is_server,
                               preread=preread)
    discipline = platform.discipline
    key = pool_key or _fmt_addr(sock, peer=True)
    # Pool pairs default to the shm domain (works in-process and cross-process on one
    # host).  Ring platforms require both peers on one host, the same way the
    # reference's RDMA modes require both peers on one IB fabric.
    return RingEndpoint(sock, discipline=discipline, pool_key=key,
                        preread=preread)


def tls_client_handshake(sock: socket.socket, ssl_context,
                         server_hostname: str) -> socket.socket:
    """Client-side TLS wrap with uniform failure semantics (shared by the
    endpoint factory and the h2 wire-compat client)."""
    try:
        return ssl_context.wrap_socket(sock, server_hostname=server_hostname)
    except (OSError, ValueError) as exc:
        sock.close()
        raise EndpointError(f"TLS handshake failed: {exc}") from exc


def connect_endpoint(host: str, port: int,
                     timeout: Optional[float] = 30,
                     ssl_context=None,
                     server_hostname: Optional[str] = None) -> Endpoint:
    """Client side: TCP-connect (optionally TLS-wrap), then let the factory
    pick the pipe (``tcp_client_posix.cc:124-126``).

    With ``ssl_context`` the handshake happens BEFORE platform dispatch, so
    every platform's bootstrap — including the ring address exchange and its
    notify/liveness channel — rides the encrypted stream (the reference's
    creds-work-unchanged-over-the-swapped-pipe property, SURVEY §2.4)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    if ssl_context is not None:
        sock = tls_client_handshake(sock, ssl_context,
                                    server_hostname or host)
    sock.settimeout(None)
    return create_endpoint(sock, is_server=False, pool_key=f"{host}:{port}")


#: connections closed at the accept gate before any handshake work
_ACCEPT_SHED = _metrics.counter("accept_shed")


class EndpointListener:
    """Accept loop feeding the factory (``tcp_server_posix.cc:267``).

    ISSUE 16 accept-storm hardening: a reconnect storm after a shard
    death lands the whole listen backlog at once. Two defenses, both
    BEFORE any handshake work is spent on a connection:

    * **bounded burst draining** — each accept-loop turn drains up to
      ``TPURPC_ACCEPT_BURST`` queued connections in one sweep (one
      blocking accept, then non-blocking accepts) instead of one per
      0.2 s loop turn, so the backlog clears in O(backlog/burst) sweeps
      while ``close()`` stays responsive;
    * **admission pushback** — an optional ``admission()`` probe
      (``None`` = admit, int = pushback ms — the RPC server wires its
      :class:`~tpurpc.rpc.server.AdmissionGate`'s connection-level face
      here) is consulted per accepted socket, and the count of in-flight
      bootstrap handshakes is bounded, so a storm sheds with a cheap
      close + ``ACCEPT_SHED`` flight event instead of a thousand
      concurrent handshakes starving live traffic.
    """

    def __init__(self, host: str, port: int,
                 on_endpoint: Callable[[Endpoint], None],
                 ready: "Optional[threading.Event]" = None,
                 ssl_context=None,
                 raw_hook: "Optional[Callable[[socket.socket], bool]]" = None,
                 reuseport: bool = False,
                 admission: "Optional[Callable[[], Optional[int]]]" = None):
        #: pre-endpoint interception seam: called with the RAW accepted
        #: socket (plaintext listeners only); returning True means the hook
        #: took ownership (the native-server adoption path,
        #: rpc/native_server.py) and no Endpoint is built
        self._raw_hook = raw_hook
        self._ssl_context = ssl_context
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuseport:
            # tpurpc-manycore listener sharding: N worker processes listen
            # on the SAME port and the kernel spreads accepted connections
            # across them (the SO_REUSEPORT accept spread — no supervisor
            # in the accept path at all). Every sharing socket must set the
            # flag before bind; a dead worker's socket closes with it, so
            # the kernel stops routing there without coordination.
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._on_endpoint = on_endpoint
        self._admission = admission
        self._burst = max(1, get_config().accept_burst)
        #: in-flight bootstrap handshakes — bounded so a storm cannot
        #: spawn unbounded handshake threads; guarded by _handshakes_mu
        self._handshakes = 0
        self._max_handshakes = max(self._burst * 4, 64)
        self._handshakes_mu = threading.Lock()
        self._ftag = _flight.tag_for(f"accept:{self.port}")
        # grpcio semantics: the port is bound (connects land in the listen
        # backlog) but nothing is accepted until the server starts — otherwise
        # an early client could race method registration into UNIMPLEMENTED.
        self._ready = ready
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"tpurpc-accept-{self.port}")
        self._thread.start()

    def _loop(self) -> None:
        # Periodic timeout so close() from another thread is observed: closing an
        # fd does NOT wake a thread blocked in accept(2), and the blocked accept's
        # reference keeps the listening socket (and the port) alive.
        self._sock.settimeout(0.2)
        if self._ready is not None:
            while not self._stopped and not self._ready.wait(timeout=0.2):
                pass
        while not self._stopped:
            try:
                sock, addr = self._sock.accept()
                sock.settimeout(None)
            except socket.timeout:
                continue
            except OSError as exc:
                if self._stopped:
                    return
                # Transient accept failures (EMFILE, ECONNABORTED...) must not
                # kill the accept loop while the listen socket stays bound.
                trace_endpoint.log("accept failed (%s); continuing", exc)
                time.sleep(0.05)
                continue
            # Bounded burst drain: the rest of the backlog is sitting in
            # the kernel queue right now — take up to accept_burst of it
            # in this sweep rather than one connection per loop turn.
            batch = [(sock, addr)]
            self._sock.settimeout(0)
            try:
                while len(batch) < self._burst and not self._stopped:
                    try:
                        s2, a2 = self._sock.accept()
                    except (BlockingIOError, socket.timeout):
                        break
                    except OSError:
                        break
                    s2.settimeout(None)
                    batch.append((s2, a2))
            finally:
                self._sock.settimeout(0.2)
            for s, a in batch:
                self._dispatch(s, a)

    def _dispatch(self, sock: socket.socket, addr) -> None:
        """Admission gate, then bootstrap off the accept thread: a ring
        handshake blocks (bounded by BOOTSTRAP_TIMEOUT_S), and one silent
        client must not stall every other accept behind it. Shedding
        happens HERE — before TLS, before the protocol sniff, before any
        endpoint state — so an overloaded server's cost per stormed
        connection is one accept + one close."""
        pushback = None
        if self._admission is not None:
            try:
                pushback = self._admission()
            except Exception:
                pushback = None  # a broken probe never sheds
        with self._handshakes_mu:
            inflight = self._handshakes
            if pushback is None and inflight >= self._max_handshakes:
                # the handshake plane itself is the bottleneck: shed with
                # a nominal pushback rather than queue threads unboundedly
                pushback = 50
            if pushback is None:
                self._handshakes = inflight + 1
        if pushback is not None:
            pushback = int(pushback)
            _ACCEPT_SHED.inc()
            _flight.emit(_flight.ACCEPT_SHED, self._ftag, inflight,
                         pushback)
            try:
                sock.close()
            except OSError:
                pass
            return
        threading.Thread(target=self._bootstrap, args=(sock, addr),
                         daemon=True,
                         name=f"tpurpc-bootstrap-{self.port}").start()

    def _bootstrap(self, sock: socket.socket, addr) -> None:
        try:
            self._bootstrap_inner(sock, addr)
        finally:
            with self._handshakes_mu:
                self._handshakes = max(0, self._handshakes - 1)

    def _bootstrap_inner(self, sock: socket.socket, addr) -> None:
        if self._raw_hook is not None and self._ssl_context is None:
            try:
                if self._raw_hook(sock):
                    return  # hook owns the socket now
            except Exception as exc:
                trace_endpoint.log("raw hook failed (%s); python path", exc)
        try:
            if self._ssl_context is not None:
                # Handshake before dispatch: the platform sniff/bootstrap
                # reads DECRYPTED bytes. A client speaking plaintext (or bad
                # certs) fails here, never reaching the protocol layer.
                sock.settimeout(20)
                sock = self._ssl_context.wrap_socket(sock, server_side=True)
                sock.settimeout(None)
            # Server keys pooled pairs by peer host (ref rule: server keys by
            # peer, rdma_bp_posix.cc:748-763) — ephemeral ports would defeat
            # reuse entirely.
            ep = create_endpoint(sock, is_server=True,
                                 pool_key=f"peer:{addr[0]}")
        except Exception as exc:
            trace_endpoint.log("accept bootstrap failed: %s", exc)
            sock.close()
            return
        if self._stopped:
            ep.close()
            return
        self._on_endpoint(ep)

    def close(self) -> None:
        self._stopped = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)  # wakes a blocked accept on Linux
        except OSError:
            pass
        self._thread.join(timeout=5)
        try:
            self._sock.close()
        except OSError:
            pass
