"""Process-wide timer wheel — one thread for every scheduled deadline.

The reference's iomgr has a timer subsystem (``iomgr/timer.cc``: a heap of
deadlines serviced by the timer thread) precisely because spawning a thread
per timer is unaffordable on hot paths. ``threading.Timer`` is exactly
that unaffordable thing (~100µs thread spawn per arm — measured turning
the inline-handler deadline watchdog into a 25% RPC-rate regression).

    handle = schedule(0.3, fn)   # fn() on the wheel thread after 0.3s
    handle.cancel()              # best-effort; no-op if already fired

Callbacks run on the single wheel thread and must be short/non-blocking
(they get the same contract as iomgr timer closures). Exceptions are
swallowed with a traceback to stderr — one bad callback must not kill
every timer in the process.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import traceback
from typing import Callable, Optional


class TimerHandle:
    """Cancellation is flag-based (the heap entry stays until its deadline)
    but the callback reference is dropped EAGERLY: a cancelled 2-hour
    keepalive timer must not pin its connection closure (endpoint, buffers)
    for 2 hours."""

    __slots__ = ("cancelled", "fn")

    def __init__(self, fn: Callable[[], None]):
        self.cancelled = False
        self.fn: Optional[Callable[[], None]] = fn

    def cancel(self) -> None:
        self.cancelled = True
        self.fn = None  # release the closure (and everything it captures)


class TimerWheel:
    _instance: "Optional[TimerWheel]" = None
    _instance_lock = threading.Lock()

    @classmethod
    def get(cls) -> "TimerWheel":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = TimerWheel()
            return cls._instance

    def __init__(self):
        self._cond = threading.Condition()
        self._heap: list = []
        self._seq = itertools.count()  # tie-break: heap never compares fns
        self._thread: Optional[threading.Thread] = None

    def schedule(self, delay_s: float, fn: Callable[[], None]) -> TimerHandle:
        handle = TimerHandle(fn)
        when = time.monotonic() + max(0.0, delay_s)
        with self._cond:
            heapq.heappush(self._heap, (when, next(self._seq), handle))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._run, daemon=True,
                                                name="tpurpc-timers")
                self._thread.start()
            self._cond.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    now = time.monotonic()
                    if not self._heap:
                        # park until new work (bounded: a dead wheel thread
                        # is restarted by schedule(), but don't exit eagerly
                        # and churn threads under bursty load)
                        self._cond.wait(timeout=60.0)
                        if not self._heap:
                            return  # idle a full minute: let the thread go
                        continue
                    when = self._heap[0][0]
                    if when <= now:
                        _, _, handle = heapq.heappop(self._heap)
                        break
                    self._cond.wait(timeout=when - now)
            fn = handle.fn
            if handle.cancelled or fn is None:
                continue
            try:
                fn()
            except Exception:
                traceback.print_exc()


def schedule(delay_s: float, fn: Callable[[], None]) -> TimerHandle:
    """Module-level convenience over the singleton wheel."""
    return TimerWheel.get().schedule(delay_s, fn)


_blocking_pool = None
_blocking_lock = threading.Lock()


def run_blocking(fn: Callable[[], None]) -> None:
    """Run ``fn`` off the wheel thread (small shared daemon pool).

    Wheel callbacks must not block — but timer-driven WORK often does
    (keepalive PINGs and GOAWAYs are endpoint writes that can stall on
    transport backpressure; teardown closes fds). One blocked send on the
    wheel would freeze every timer in the process; here it occupies one of
    a few shared workers instead (still bounded, still not per-connection
    threads)."""
    global _blocking_pool
    with _blocking_lock:
        if _blocking_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _blocking_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="tpurpc-timerio")
        pool = _blocking_pool
    pool.submit(fn)
