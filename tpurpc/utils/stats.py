"""Profiling spans and copy-ledger counters.

Reference: the fork's profiling subsystem (SURVEY.md §2.5/§5) — ``GRPCProfiler`` RAII
spans feeding per-thread HdrHistogram slots for ~30 instrumented ops
(``include/grpcpp/stats_time.h:11-44``), enabled by ``GRPC_PROFILING`` /
``GRPC_PROFILING_UNIT`` (``src/core/lib/debug/stats_time.cc:25-45``), printed as an
ASCII table at shutdown (``stats_time.cc:161-246`` via ``debug/VariadicTable.h``).

tpurpc keeps the same shape — named spans, per-thread accumulation, a table printer —
plus one thing the reference does not have: a **copy ledger** counting host-memcpy bytes
on the receive path, because the north star (BASELINE.md) is "host-memcpy bytes = 0" and
an unmeasured claim is worthless.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple


def _enabled() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_PROFILING", "GRPC_PROFILING") or "").lower() in (
        "1", "true", "micro", "on")


class _Hist:
    """Tiny log-bucketed latency histogram (stand-in for HdrHistogram_c)."""

    __slots__ = ("buckets", "count", "total_ns", "max_ns")

    def __init__(self):
        self.buckets = [0] * 64
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0

    def record(self, ns: int) -> None:
        self.count += 1
        self.total_ns += ns
        if ns > self.max_ns:
            self.max_ns = ns
        self.buckets[min(63, max(0, ns.bit_length()))] += 1

    def percentile(self, q: float) -> float:
        """Log-bucketed percentile with within-bucket interpolation.

        Bucket ``i`` holds values whose bit_length is ``i``, i.e. the
        half-open range ``[2^(i-1), 2^i)``. The old behavior returned the
        bucket's UPPER bound, so a reported p50/p99 could run ~2x high;
        interpolating linearly inside the bucket keeps the error within
        the bucket's own resolution."""
        if self.count == 0:
            return 0.0
        target = math.ceil(self.count * q)
        seen = 0
        for i, n in enumerate(self.buckets):
            if not n:
                continue
            if seen + n >= target:
                lo = 0.0 if i == 0 else float(1 << (i - 1))
                hi = float(1 << i)
                frac = (target - seen) / n
                return min(lo + frac * (hi - lo), float(self.max_ns))
            seen += n
        return float(self.max_ns)


class _ThreadSlots(threading.local):
    def __init__(self):
        self.slots: Dict[str, _Hist] = defaultdict(_Hist)
        self.registered = False


_tls = _ThreadSlots()
_all_slots_lock = threading.Lock()
_all_slots: List[Dict[str, _Hist]] = []
_force_enabled: Optional[bool] = None


def enable(on: bool = True) -> None:
    """Programmatic switch, like ``grpc_stats_time_enable`` (stats_time.cc:47-58)."""
    global _force_enabled
    _force_enabled = on


def profiling_on() -> bool:
    return _force_enabled if _force_enabled is not None else _enabled()


class profile:
    """``with profile("op"):`` span — the ``GRPCProfiler`` RAII equivalent."""

    __slots__ = ("op", "t0")

    def __init__(self, op: str):
        self.op = op
        self.t0 = 0

    def __enter__(self):
        if profiling_on():
            self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self.t0:
            if not _tls.registered:
                with _all_slots_lock:
                    _all_slots.append(_tls.slots)
                _tls.registered = True
            _tls.slots[self.op].record(time.perf_counter_ns() - self.t0)
            self.t0 = 0
        return False


def snapshot() -> Dict[str, Tuple[int, float, float, float]]:
    """op → (count, mean_us, p50_us, p99_us) merged across threads."""
    merged: Dict[str, _Hist] = defaultdict(_Hist)
    with _all_slots_lock:
        slot_dicts = list(_all_slots)
    for slots in slot_dicts:
        # Other threads keep recording while we read; retry if the dict resizes
        # under us.  Slightly torn counts are fine for stats; crashing is not.
        for _ in range(5):
            try:
                items = list(slots.items())
                break
            except RuntimeError:
                continue
        else:
            items = []
        for op, h in items:
            m = merged[op]
            m.count += h.count
            m.total_ns += h.total_ns
            m.max_ns = max(m.max_ns, h.max_ns)
            for i, n in enumerate(h.buckets):
                m.buckets[i] += n
    return {
        op: (
            h.count,
            (h.total_ns / h.count / 1e3) if h.count else 0.0,
            h.percentile(0.5) / 1e3,
            h.percentile(0.99) / 1e3,
        )
        for op, h in merged.items()
    }


def print_table() -> str:
    """ASCII table like ``grpc_stats_time_print`` (stats_time.cc:161-246)."""
    rows = snapshot()
    if not rows:
        return "(no profiling data)"
    header = f"{'op':<32} {'count':>10} {'mean(us)':>10} {'p50(us)':>10} {'p99(us)':>10}"
    lines = [header, "-" * len(header)]
    for op in sorted(rows):
        c, mean, p50, p99 = rows[op]
        lines.append(f"{op:<32} {c:>10} {mean:>10.2f} {p50:>10.1f} {p99:>10.1f}")
    out = "\n".join(lines)
    print(out)
    return out


# ---------------------------------------------------------------------------
# Batch + wakeup counters — the batched receive pipeline's observability.
#
# Always on (unlike the profile spans): one lock-guarded integer bump per
# BATCH, which is exactly the amortization the pipeline exists to buy — if
# these counters were per-message they would be part of the problem they
# measure. The bench reads them to report batch_msgs_per_wakeup and the
# adaptive poller's spin/sleep ratio (ISSUE 1 acceptance).
#
# Since ISSUE 4 the STORE is the tpurpc-scope metrics registry
# (tpurpc/obs/metrics.py) — these functions are the stable façade PR 1's
# call sites keep using, with no parallel bookkeeping behind them: the same
# objects feed the Prometheus scrape endpoint.
# ---------------------------------------------------------------------------

from tpurpc.obs import metrics as _metrics  # noqa: E402

#: compat alias: PR 1's BatchHist is the registry's exact-count histogram
BatchHist = _metrics.Histogram


def batch_hist(name: str) -> "_metrics.Histogram":
    """Named batch-size histogram (created on first use). Canonical names:
    ``ring_drain`` (messages per receive drain), ``ring_write`` (messages
    per gathered send batch), ``h2_data_coalesce`` (DATA frames merged per
    dispatch), ``resp_coalesce`` (responses per gathered server writev),
    ``fanin_batch`` (rows per dispatched fan-in batch)."""
    return _metrics.histogram(name, kind="size")


def counter_inc(name: str, n: int = 1) -> None:
    """Bump a named monotonic counter. Canonical names: ``wait_spin_hit`` /
    ``wait_spin_miss`` (hybrid busy window fired / expired), ``wait_sleep``
    (waiter parked on fds), ``poller_scan_hot`` / ``poller_scan_idle``
    (background scans that found / did not find work)."""
    _metrics.counter(name).inc(n)


def counters_snapshot() -> Dict[str, int]:
    return _metrics.registry().counters_snapshot()


def batch_snapshot() -> Dict[str, Dict[str, float]]:
    return _metrics.registry().histograms_snapshot()


def reset_batch_stats() -> None:
    """Zero the registry's histograms/counters (bench round isolation)."""
    _metrics.reset()


# ---------------------------------------------------------------------------
# Copy ledger — new in tpurpc (BASELINE.md target: receive-path host memcpy == 0).
# Folded onto the metrics registry (ISSUE 4): each category is a registry
# counter named ``copyledger_<category>``, so the Prometheus endpoint sees
# the same numbers with zero duplicate accounting.
# ---------------------------------------------------------------------------

class CopyLedger:
    """Counts bytes moved by each mechanism on the hot paths.

    Categories:
      host_copy      — CPU memcpy through host DRAM (what we are eliminating)
      device_dma     — NIC/DMA bytes landing directly in device memory
      device_alias   — bytes surfaced zero-copy (aliased, no move at all)
      host_staged    — bytes bounced host→device because true DMA is unavailable
    """

    CATEGORIES = ("host_copy", "device_dma", "device_alias", "host_staged")

    def __init__(self):
        self._counters = {c: _metrics.counter(f"copyledger_{c}")
                          for c in self.CATEGORIES}

    def add(self, category: str, nbytes: int) -> None:
        c = self._counters.get(category)
        if c is None:
            raise ValueError(
                f"unknown copy-ledger category {category!r}; "
                f"expected one of {self.CATEGORIES}")
        c.inc(nbytes)

    def reset(self) -> None:
        for c in self._counters.values():
            c.reset()

    def as_dict(self) -> Dict[str, int]:
        return {name: c.snapshot() for name, c in self._counters.items()}

    def __getattr__(self, name: str) -> int:
        counters = self.__dict__.get("_counters") or {}
        if name in counters:
            return counters[name].snapshot()
        raise AttributeError(name)


ledger = CopyLedger()
