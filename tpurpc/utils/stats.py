"""Profiling spans and copy-ledger counters.

Reference: the fork's profiling subsystem (SURVEY.md §2.5/§5) — ``GRPCProfiler`` RAII
spans feeding per-thread HdrHistogram slots for ~30 instrumented ops
(``include/grpcpp/stats_time.h:11-44``), enabled by ``GRPC_PROFILING`` /
``GRPC_PROFILING_UNIT`` (``src/core/lib/debug/stats_time.cc:25-45``), printed as an
ASCII table at shutdown (``stats_time.cc:161-246`` via ``debug/VariadicTable.h``).

tpurpc keeps the same shape — named spans, per-thread accumulation, a table printer —
plus one thing the reference does not have: a **copy ledger** counting host-memcpy bytes
on the receive path, because the north star (BASELINE.md) is "host-memcpy bytes = 0" and
an unmeasured claim is worthless.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple


def _enabled() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_PROFILING", "GRPC_PROFILING") or "").lower() in (
        "1", "true", "micro", "on")


class _Hist:
    """Tiny log-bucketed latency histogram (stand-in for HdrHistogram_c)."""

    __slots__ = ("buckets", "count", "total_ns", "max_ns")

    def __init__(self):
        self.buckets = [0] * 64
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0

    def record(self, ns: int) -> None:
        self.count += 1
        self.total_ns += ns
        if ns > self.max_ns:
            self.max_ns = ns
        self.buckets[min(63, max(0, ns.bit_length()))] += 1

    def percentile(self, q: float) -> float:
        """Approximate: returns the upper bound of the bucket holding quantile q."""
        if self.count == 0:
            return 0.0
        target = math.ceil(self.count * q)
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                return float(1 << i)
        return float(self.max_ns)


class _ThreadSlots(threading.local):
    def __init__(self):
        self.slots: Dict[str, _Hist] = defaultdict(_Hist)
        self.registered = False


_tls = _ThreadSlots()
_all_slots_lock = threading.Lock()
_all_slots: List[Dict[str, _Hist]] = []
_force_enabled: Optional[bool] = None


def enable(on: bool = True) -> None:
    """Programmatic switch, like ``grpc_stats_time_enable`` (stats_time.cc:47-58)."""
    global _force_enabled
    _force_enabled = on


def profiling_on() -> bool:
    return _force_enabled if _force_enabled is not None else _enabled()


class profile:
    """``with profile("op"):`` span — the ``GRPCProfiler`` RAII equivalent."""

    __slots__ = ("op", "t0")

    def __init__(self, op: str):
        self.op = op
        self.t0 = 0

    def __enter__(self):
        if profiling_on():
            self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self.t0:
            if not _tls.registered:
                with _all_slots_lock:
                    _all_slots.append(_tls.slots)
                _tls.registered = True
            _tls.slots[self.op].record(time.perf_counter_ns() - self.t0)
            self.t0 = 0
        return False


def snapshot() -> Dict[str, Tuple[int, float, float, float]]:
    """op → (count, mean_us, p50_us, p99_us) merged across threads."""
    merged: Dict[str, _Hist] = defaultdict(_Hist)
    with _all_slots_lock:
        slot_dicts = list(_all_slots)
    for slots in slot_dicts:
        # Other threads keep recording while we read; retry if the dict resizes
        # under us.  Slightly torn counts are fine for stats; crashing is not.
        for _ in range(5):
            try:
                items = list(slots.items())
                break
            except RuntimeError:
                continue
        else:
            items = []
        for op, h in items:
            m = merged[op]
            m.count += h.count
            m.total_ns += h.total_ns
            m.max_ns = max(m.max_ns, h.max_ns)
            for i, n in enumerate(h.buckets):
                m.buckets[i] += n
    return {
        op: (
            h.count,
            (h.total_ns / h.count / 1e3) if h.count else 0.0,
            h.percentile(0.5) / 1e3,
            h.percentile(0.99) / 1e3,
        )
        for op, h in merged.items()
    }


def print_table() -> str:
    """ASCII table like ``grpc_stats_time_print`` (stats_time.cc:161-246)."""
    rows = snapshot()
    if not rows:
        return "(no profiling data)"
    header = f"{'op':<32} {'count':>10} {'mean(us)':>10} {'p50(us)':>10} {'p99(us)':>10}"
    lines = [header, "-" * len(header)]
    for op in sorted(rows):
        c, mean, p50, p99 = rows[op]
        lines.append(f"{op:<32} {c:>10} {mean:>10.2f} {p50:>10.1f} {p99:>10.1f}")
    out = "\n".join(lines)
    print(out)
    return out


# ---------------------------------------------------------------------------
# Batch + wakeup counters — the batched receive pipeline's observability.
#
# Always on (unlike the profile spans): one lock-guarded integer bump per
# BATCH, which is exactly the amortization the pipeline exists to buy — if
# these counters were per-message they would be part of the problem they
# measure. The bench reads them to report batch_msgs_per_wakeup and the
# adaptive poller's spin/sleep ratio (ISSUE 1 acceptance).
# ---------------------------------------------------------------------------

class BatchHist:
    """Thread-safe size histogram for per-batch counts.

    Batch sizes are small integers, so counts are EXACT below
    ``_EXACT_MAX`` (percentiles come out precise, unlike the log-bucketed
    latency hist whose bucket upper bounds would double-count small
    batches); larger sizes clamp into the top bucket."""

    _EXACT_MAX = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = defaultdict(int)
        self._total = 0
        self._n = 0
        self._max = 0

    def record(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._counts[min(n, self._EXACT_MAX)] += 1
            self._total += n
            self._n += 1
            if n > self._max:
                self._max = n

    def _percentile_locked(self, q: float) -> int:
        target = math.ceil(self._n * q)
        seen = 0
        for size in sorted(self._counts):
            seen += self._counts[size]
            if seen >= target:
                return size
        return self._max

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self._n == 0:
                return {"count": 0, "mean": 0.0, "p50": 0, "p99": 0, "max": 0}
            return {
                "count": self._n,
                "mean": round(self._total / self._n, 2),
                "p50": self._percentile_locked(0.5),
                "p99": self._percentile_locked(0.99),
                "max": self._max,
            }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._total = 0
            self._n = 0
            self._max = 0


_batch_lock = threading.Lock()
_batch_hists: Dict[str, BatchHist] = {}
_counters: Dict[str, int] = defaultdict(int)
_counter_lock = threading.Lock()


def batch_hist(name: str) -> BatchHist:
    """Named batch-size histogram (created on first use). Canonical names:
    ``ring_drain`` (messages per receive drain), ``ring_write`` (messages
    per gathered send batch), ``h2_data_coalesce`` (DATA frames merged per
    dispatch)."""
    with _batch_lock:
        h = _batch_hists.get(name)
        if h is None:
            h = _batch_hists[name] = BatchHist()
        return h


def counter_inc(name: str, n: int = 1) -> None:
    """Bump a named monotonic counter. Canonical names: ``wait_spin_hit`` /
    ``wait_spin_miss`` (hybrid busy window fired / expired), ``wait_sleep``
    (waiter parked on fds), ``poller_scan_hot`` / ``poller_scan_idle``
    (background scans that found / did not find work)."""
    with _counter_lock:
        _counters[name] += n


def counters_snapshot() -> Dict[str, int]:
    with _counter_lock:
        return dict(_counters)


def batch_snapshot() -> Dict[str, Dict[str, float]]:
    with _batch_lock:
        hists = dict(_batch_hists)
    return {name: h.snapshot() for name, h in hists.items()}


def reset_batch_stats() -> None:
    """Zero the batch histograms and counters (bench round isolation)."""
    with _batch_lock:
        for h in _batch_hists.values():
            h.reset()
    with _counter_lock:
        _counters.clear()


# ---------------------------------------------------------------------------
# Copy ledger — new in tpurpc (BASELINE.md target: receive-path host memcpy == 0).
# ---------------------------------------------------------------------------

class CopyLedger:
    """Counts bytes moved by each mechanism on the hot paths.

    Categories:
      host_copy      — CPU memcpy through host DRAM (what we are eliminating)
      device_dma     — NIC/DMA bytes landing directly in device memory
      device_alias   — bytes surfaced zero-copy (aliased, no move at all)
      host_staged    — bytes bounced host→device because true DMA is unavailable
    """

    CATEGORIES = ("host_copy", "device_dma", "device_alias", "host_staged")

    def __init__(self):
        self._lock = threading.Lock()
        self.host_copy = 0
        self.device_dma = 0
        self.device_alias = 0
        self.host_staged = 0

    def add(self, category: str, nbytes: int) -> None:
        if category not in self.CATEGORIES:
            raise ValueError(
                f"unknown copy-ledger category {category!r}; "
                f"expected one of {self.CATEGORIES}")
        with self._lock:
            setattr(self, category, getattr(self, category) + nbytes)

    def reset(self) -> None:
        with self._lock:
            self.host_copy = self.device_dma = 0
            self.device_alias = self.host_staged = 0

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return {
                "host_copy": self.host_copy,
                "device_dma": self.device_dma,
                "device_alias": self.device_alias,
                "host_staged": self.host_staged,
            }


ledger = CopyLedger()
