from tpurpc.utils.config import Config, Platform, get_config, set_config
from tpurpc.utils import trace

__all__ = ["Config", "Platform", "get_config", "set_config", "trace"]
