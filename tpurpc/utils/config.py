"""Typed runtime configuration.

The reference spreads configuration over four mechanisms (SURVEY.md §5): env vars
(platform switch ``GRPC_PLATFORM_TYPE`` in ``iomgr_internal.cc:36-61``; the new-gen
``GRPC_RDMA_*`` family in ``src/core/lib/ibverbs/config.cc:48-113``; the old-gen family in
``src/core/lib/rdma/rdma_utils.h:22-106``), channel args, GPR global-config strings, and
benchmark flags.  tpurpc collapses them into this one typed layer while keeping the
documented UX: the transport is still selected by an env var at process start, and every
reference knob has a ``TPURPC_*`` spelling plus its original ``GRPC_RDMA_*`` /
``GRPC_PLATFORM_TYPE`` alias so a reference user's environment keeps working.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
from typing import Optional, Tuple


class Platform(enum.Enum):
    """Which byte-pipe ``tpurpc.core.endpoint.create_endpoint`` dispatches to.

    Mirrors ``platform_t{IOMGR_TCP, IOMGR_RDMA_BP, IOMGR_RDMA_BPEV, IOMGR_RDMA_EVENT}``
    (reference ``iomgr_internal.h:45``).  The RDMA modes map onto ring-buffer transports
    with the same three wakeup disciplines; ``TPU`` is the new mode whose receive ring is
    device(HBM)-resident.
    """

    TCP = "TCP"
    RING_BP = "RING_BP"        # busy-poll            (ref: RDMA_BP)
    RING_EVENT = "RING_EVENT"  # event/interrupt      (ref: RDMA_EVENT)
    RING_BPEV = "RING_BPEV"    # hybrid spin-then-block (ref: RDMA_BPEV, the default perf mode)
    TPU = "TPU"                # HBM-resident receive ring + zero-copy jax.Array recv

    @property
    def is_ring(self) -> bool:
        return self is not Platform.TCP

    @property
    def discipline(self) -> Optional[str]:
        """Wakeup discipline for ring platforms; None for TCP.  Single source of
        truth for the platform→discipline mapping (ref: platform→poll-strategy
        forcing, ``ev_posix.cc:225-232``)."""
        return {
            Platform.RING_BP: "busy",
            Platform.RING_EVENT: "event",
            Platform.RING_BPEV: "hybrid",
            Platform.TPU: "hybrid",
        }.get(self)


# Accept the reference's spellings verbatim (README.md:17-25 documents these values).
_PLATFORM_ALIASES = {
    "TCP": Platform.TCP,
    "RDMA_BP": Platform.RING_BP,
    "RDMA_EVENT": Platform.RING_EVENT,
    "RDMA_BPEV": Platform.RING_BPEV,
    "RING_BP": Platform.RING_BP,
    "RING_EVENT": Platform.RING_EVENT,
    "RING_BPEV": Platform.RING_BPEV,
    "TPU": Platform.TPU,
    "RDMA_TPU": Platform.TPU,  # BASELINE.json north-star spelling
}


def env_lookup(name: str, *aliases: str) -> Tuple[Optional[str], Optional[str]]:
    """First non-empty value among ``name`` and its aliases → (key_found, value).

    Empty-string values count as unset (so ``TPURPC_X="" GRPC_X=y`` falls through to
    the alias).  This is THE env-with-fallback helper — trace/stats reuse it so the
    semantics are identical everywhere.
    """
    for key in (name, *aliases):
        val = os.environ.get(key)
        if val is not None and val != "":
            return key, val
    return None, None


def _env(name: str, *aliases: str) -> Optional[str]:
    return env_lookup(name, *aliases)[1]


def _env_int(name: str, default: int, *aliases: str) -> int:
    key, val = env_lookup(name, *aliases)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError as exc:
        raise ValueError(f"{key}={val!r} is not an integer") from exc


def _env_float(name: str, default: float, *aliases: str) -> float:
    key, val = env_lookup(name, *aliases)
    if val is None:
        return default
    try:
        return float(val)
    except ValueError as exc:
        raise ValueError(f"{key}={val!r} is not a number") from exc


def _env_bool(name: str, default: bool, *aliases: str) -> bool:
    val = _env(name, *aliases)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Config:
    """Process-wide config snapshot, read once from the environment.

    Field ↔ reference-knob map (citations into /root/reference):

    ==========================  =====================================================
    platform                    GRPC_PLATFORM_TYPE        iomgr_internal.cc:36-61
    ring_buffer_size_kb         GRPC_RDMA_RING_BUFFER_SIZE_KB   config.cc:93-101 (default 4MB, README:17-25)
    poller_thread_num           GRPC_RDMA_POLLER_THREAD_NUM     config.cc:67-74  (default 1)
    busy_polling_timeout_us     GRPC_RDMA_BUSY_POLLING_TIMEOUT_US config.cc:75-83 (default 500us)
    poller_sleep_timeout_ms     GRPC_RDMA_POLLER_SLEEP_TIMEOUT_MS config.cc:84-92 (default 1000ms)
    zerocopy_threshold_kb       GRPC_RDMA_ZEROCOPY_THRESHOLD_KB  config.cc:102-113
                                (reference default = uint32 max, i.e. DISABLED; -1 here)
    send_chunk_size             GRPC_RDMA_SEND_CHUNK_SIZE  rdma_utils.h:87-92 (default 512KB)
    zerocopy_enable             GRPC_RDMA_ZEROCOPY_ENABLE  rdma_utils.h:93-97
    polling_yield               GRPC_RDMA_POLLING_YIELD    rdma_utils.h:75-80
    device_ordinal              (TPU analog of GRPC_RDMA_DEVICE_NAME/PORT/GID, config.cc:48-66)
    pair_pool_size              kInitPoolSize=128          pair.h:273-333
    poller_capacity             kMaxPairs=4096             poller.h:12
    ==========================  =====================================================
    """

    platform: Platform = Platform.TCP
    ring_buffer_size_kb: int = 4096
    poller_thread_num: int = 1
    busy_polling_timeout_us: int = 500
    poller_sleep_timeout_ms: int = 1000
    zerocopy_threshold_kb: int = -1  # -1 = disabled, matching config.cc:108-113
    send_chunk_size: int = 512 * 1024
    zerocopy_enable: bool = True
    polling_yield: bool = True  # unset env means yield ON (rdma_utils.h:76-77)
    device_ordinal: int = 0
    pair_pool_size: int = 128
    poller_capacity: int = 4096
    #: Device (HBM) receive-ring capacity for Platform.TPU endpoints — the
    #: analog of ring_buffer_size_kb for the device-resident ring. Default
    #: 16 MiB: four in-flight 4 MiB tensors per connection.
    hbm_ring_size_kb: int = 16384
    #: Largest acceptable received message, bytes (-1 = unlimited). The
    #: grpc.max_receive_message_length analog, sized for tensor traffic
    #: (grpcio's 4 MiB default would reject one float32[1024,1024] payload).
    max_recv_message_length: int = 64 << 20
    #: Completed-but-unconsumed messages buffered per stream before the
    #: connection reader stops draining the transport (backpressure; the
    #: ring's credit flow then stalls the sender). resource_quota.cc's role,
    #: expressed in messages instead of bytes.
    stream_queue_depth: int = 64
    #: Client keepalive: PING the server every N ms of inactivity; 0/neg
    #: disables (gRPC's default — keepalive off unless configured). Accepts
    #: gRPC's channel-arg spelling GRPC_ARG_KEEPALIVE_TIME_MS as an env var
    #: for parity with the reference's knob family.
    keepalive_time_ms: int = 0
    #: How long to wait for the keepalive PONG before declaring the
    #: connection dead (GRPC_ARG_KEEPALIVE_TIMEOUT_MS; default 20 s).
    keepalive_timeout_ms: int = 20000
    #: client_idle filter analog: close a client connection with no streams
    #: after this much inactivity; 0/neg disables (the default here —
    #: gRPC's filter defaults to 30 min when configured).
    client_idle_timeout_ms: int = 0
    #: max_age filter analog: server sends GOAWAY on connections older than
    #: this; in-flight calls drain, new calls dial fresh. 0/neg disables.
    max_connection_age_ms: int = 0
    #: Which MemoryDomain carries the ring's one-sided writes: "shm"
    #: (cross-process, one host — the default), "local" (in-process), or
    #: "tcp_window" (cross-HOST over an ordered record socket,
    #: tpurpc/core/tcpw.py). The analog of the reference choosing the
    #: ibverbs device for its pairs; must match on both peers (asserted at
    #: bootstrap like the reference's tag/size match, pair.cc:148-149).
    ring_domain: str = "shm"
    #: tcp_window only: the address peers should dial to reach this
    #: process's record server (advertised inside region handles), and the
    #: local bind address. Set tcpw_host to the host's reachable IP for
    #: real cross-host deployments.
    tcpw_host: str = "127.0.0.1"
    tcpw_bind: str = "0.0.0.0"
    #: tpurpc-hive (ISSUE 16): park a pair whose rings have been quiet this
    #: many seconds — its ring regions return to the shared RingPool and its
    #: poller slot frees, leaving a ~200-byte stub until the next byte.
    #: 0 (the default) disables parking entirely; the C100K deployments the
    #: RDMAvisor analysis targets opt in explicitly.
    pair_park_s: float = 0.0
    #: bound on how many extra pending accepts one listener wakeup may
    #: drain (the accept-storm burst); each drained socket still passes the
    #: admission gate before any handshake work is spent on it
    accept_burst: int = 64

    @property
    def ring_buffer_size(self) -> int:
        """Ring capacity in bytes; rounded up to a power of two like the reference
        (``ring_buffer.cc:22`` asserts power-of-two capacity)."""
        size = self.ring_buffer_size_kb * 1024
        return 1 << max(12, (size - 1).bit_length())

    @property
    def zerocopy_threshold(self) -> int:
        """Payload size (bytes) at or above which sends use the zero-copy path.

        Disabled (never triggers) when ``zerocopy_threshold_kb < 0``, mirroring the
        reference's uint32-max default (``config.cc:108-113``)."""
        if self.zerocopy_threshold_kb < 0:
            return 1 << 62
        return self.zerocopy_threshold_kb * 1024

    @classmethod
    def from_env(cls) -> "Config":
        raw = _env("TPURPC_PLATFORM_TYPE", "GRPC_PLATFORM_TYPE")
        if raw is None:
            platform = Platform.TCP
        else:
            try:
                platform = _PLATFORM_ALIASES[raw.strip().upper()]
            except KeyError:
                # The reference exits on unknown values (iomgr_internal.cc:52-59);
                # we raise, which surfaces at first Config.get().
                raise ValueError(
                    f"unknown platform type {raw!r}; expected one of "
                    f"{sorted(_PLATFORM_ALIASES)}"
                ) from None
        return cls(
            platform=platform,
            ring_buffer_size_kb=_env_int(
                "TPURPC_RING_BUFFER_SIZE_KB", cls.ring_buffer_size_kb,
                "GRPC_RDMA_RING_BUFFER_SIZE_KB"),
            poller_thread_num=_env_int(
                "TPURPC_POLLER_THREAD_NUM", cls.poller_thread_num,
                "GRPC_RDMA_POLLER_THREAD_NUM"),
            busy_polling_timeout_us=_env_int(
                "TPURPC_BUSY_POLLING_TIMEOUT_US", cls.busy_polling_timeout_us,
                "GRPC_RDMA_BUSY_POLLING_TIMEOUT_US"),
            poller_sleep_timeout_ms=_env_int(
                "TPURPC_POLLER_SLEEP_TIMEOUT_MS", cls.poller_sleep_timeout_ms,
                "GRPC_RDMA_POLLER_SLEEP_TIMEOUT_MS"),
            zerocopy_threshold_kb=_env_int(
                "TPURPC_ZEROCOPY_THRESHOLD_KB", cls.zerocopy_threshold_kb,
                "GRPC_RDMA_ZEROCOPY_THRESHOLD_KB"),
            send_chunk_size=_env_int(
                "TPURPC_SEND_CHUNK_SIZE", cls.send_chunk_size,
                "GRPC_RDMA_SEND_CHUNK_SIZE"),
            zerocopy_enable=_env_bool(
                "TPURPC_ZEROCOPY_ENABLE", cls.zerocopy_enable,
                "GRPC_RDMA_ZEROCOPY_ENABLE"),
            polling_yield=_env_bool(
                "TPURPC_POLLING_YIELD", cls.polling_yield,
                "GRPC_RDMA_POLLING_YIELD"),
            device_ordinal=_env_int("TPURPC_DEVICE_ORDINAL", cls.device_ordinal),
            pair_pool_size=_env_int("TPURPC_PAIR_POOL_SIZE", cls.pair_pool_size),
            poller_capacity=_env_int("TPURPC_POLLER_CAPACITY", cls.poller_capacity),
            hbm_ring_size_kb=_env_int(
                "TPURPC_HBM_RING_SIZE_KB", cls.hbm_ring_size_kb),
            max_recv_message_length=_env_int(
                "TPURPC_MAX_RECV_MESSAGE_LENGTH", cls.max_recv_message_length),
            stream_queue_depth=_env_int(
                "TPURPC_STREAM_QUEUE_DEPTH", cls.stream_queue_depth),
            keepalive_time_ms=_env_int(
                "TPURPC_KEEPALIVE_TIME_MS", cls.keepalive_time_ms,
                "GRPC_ARG_KEEPALIVE_TIME_MS"),
            keepalive_timeout_ms=_env_int(
                "TPURPC_KEEPALIVE_TIMEOUT_MS", cls.keepalive_timeout_ms,
                "GRPC_ARG_KEEPALIVE_TIMEOUT_MS"),
            client_idle_timeout_ms=_env_int(
                "TPURPC_CLIENT_IDLE_TIMEOUT_MS", cls.client_idle_timeout_ms,
                "GRPC_ARG_CLIENT_IDLE_TIMEOUT_MS"),
            max_connection_age_ms=_env_int(
                "TPURPC_MAX_CONNECTION_AGE_MS", cls.max_connection_age_ms,
                "GRPC_ARG_MAX_CONNECTION_AGE_MS"),
            ring_domain=(_env("TPURPC_RING_DOMAIN", "GRPC_RDMA_DOMAIN")
                         or cls.ring_domain).strip().lower(),
            tcpw_host=_env("TPURPC_TCPW_HOST") or cls.tcpw_host,
            tcpw_bind=_env("TPURPC_TCPW_BIND") or cls.tcpw_bind,
            pair_park_s=_env_float("TPURPC_PAIR_PARK_S", cls.pair_park_s),
            accept_burst=_env_int("TPURPC_ACCEPT_BURST", cls.accept_burst),
        )

    @property
    def max_recv_message_bytes(self):
        """None when unlimited (env value < 0), else the byte bound."""
        if self.max_recv_message_length < 0:
            return None
        return self.max_recv_message_length

    def resolve_recv_limit(self, override):
        """One rule for the Server/Channel option: None → config default,
        negative → unlimited (None), else the explicit byte bound."""
        if override is None:
            return self.max_recv_message_bytes
        if override < 0:
            return None
        return override

    @property
    def hbm_ring_size(self) -> int:
        """Device ring capacity in bytes, power-of-two rounded like
        :attr:`ring_buffer_size`."""
        size = self.hbm_ring_size_kb * 1024
        return 1 << max(12, (size - 1).bit_length())


_lock = threading.Lock()
_instance: Optional[Config] = None


def get_config() -> Config:
    """Lazy process-wide singleton, like ``Config::Get()`` (``config.h:13-54``)."""
    global _instance
    if _instance is None:
        with _lock:
            if _instance is None:
                _instance = Config.from_env()
    return _instance


def set_config(config: Optional[Config]) -> None:
    """Override (or with ``None`` reset) the singleton — tests and embedders only.

    The reference has no equivalent (env is read once, immutably); tests there must
    re-exec.  Being able to swap the snapshot in-process is deliberate ergonomics.
    """
    global _instance
    with _lock:
        _instance = config
