"""Trace-flag registry and leveled logging.

Mirrors the reference's ``grpc_core::TraceFlag`` registry driven by the ``GRPC_TRACE``
env var with ``GRPC_VERBOSITY`` levels (``src/core/lib/debug/trace.{h,cc}``), including
the fork-added flags ``rdma`` (``endpoint.cc:31``) and ``rdma_sr_event`` /
``rdma_sr_event_debug`` (``rdma_sender_receiver_event.cc:4-6``).  Same env grammar:
comma-separated flag names, ``all`` / ``list_tracers`` specials, ``-name`` negation.
``TPURPC_TRACE`` / ``TPURPC_VERBOSITY`` are read first, falling back to the ``GRPC_*``
names so reference debugging habits carry over.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict

_registry: Dict[str, "TraceFlag"] = {}
_registry_lock = threading.Lock()


class TraceFlag:
    """A named boolean tracing switch; cheap to test on hot paths."""

    __slots__ = ("name", "enabled")

    def __init__(self, name: str, default: bool = False):
        self.name = name
        self.enabled = default
        with _registry_lock:
            _registry[name] = self
        _apply_env_to(self)

    def __bool__(self) -> bool:
        if _list_pending:
            _print_tracers()
        return self.enabled

    def log(self, fmt: str, *args) -> None:
        if _list_pending:
            _print_tracers()
        if self.enabled:
            _emit("TRACE", f"[{self.name}] " + (fmt % args if args else fmt))


#: a ``list_tracers`` token was seen in the trace spec and the registry
#: dump hasn't printed yet — flushed on the first flag USE (by then the
#: process's flags are registered), mirroring the reference's
#: ``GRPC_TRACE=list_tracers`` one-shot listing (trace.cc LogAllTracers)
_list_pending = False


def _trace_spec() -> str:
    from tpurpc.utils.config import _env

    return _env("TPURPC_TRACE", "GRPC_TRACE") or ""


def _print_tracers() -> None:
    global _list_pending
    _list_pending = False
    with _registry_lock:
        flags = sorted(_registry.items())
    _emit("INFO", "available tracers:")
    for name, f in flags:
        _emit("INFO", f"  {name}: {'on' if f.enabled else 'off'}")


def _apply_env_to(flag: TraceFlag) -> None:
    global _list_pending
    spec = _trace_spec()
    if not spec:
        return
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok == "list_tracers":
            _list_pending = True
            continue
        neg = tok.startswith("-")
        name = tok[1:] if neg else tok
        if name == "all" or name == flag.name:
            flag.enabled = not neg


def reapply_env() -> None:
    """Re-read the trace env for every registered flag (tests use this)."""
    global _list_pending
    _list_pending = False
    with _registry_lock:
        flags = list(_registry.values())
    for f in flags:
        f.enabled = False
        _apply_env_to(f)


def list_tracers() -> Dict[str, bool]:
    with _registry_lock:
        return {name: f.enabled for name, f in _registry.items()}


# --- leveled logging (ref: gpr_log + GRPC_VERBOSITY, src/core/lib/gpr/log.cc) ---

_LEVELS = {"DEBUG": 0, "INFO": 1, "ERROR": 2, "NONE": 3}


def _verbosity() -> int:
    from tpurpc.utils.config import _env

    raw = (_env("TPURPC_VERBOSITY", "GRPC_VERBOSITY") or "ERROR").upper()
    return _LEVELS.get(raw, 2)


def _emit(level: str, msg: str) -> None:
    # ONE wall-clock read for the whole stamp: deriving the seconds and the
    # sub-second fraction from separate reads tears across a second boundary
    # (…:01.999 followed by …:01.000042). Log stamps are absolute times for
    # humans; anything computing durations uses time.monotonic().
    now = time.time()  # tpr: allow(wallclock)
    ts = time.strftime("%H:%M:%S", time.localtime(now))
    tid = threading.get_ident() & 0xFFFF
    print(f"{level[0]}{ts}.{int(now * 1e6) % 1000000:06d} {tid:5d} {msg}",
          file=sys.stderr, flush=True)


def log_debug(fmt: str, *args) -> None:
    if _verbosity() <= 0:
        _emit("DEBUG", fmt % args if args else fmt)


def log_info(fmt: str, *args) -> None:
    if _verbosity() <= 1:
        _emit("INFO", fmt % args if args else fmt)


def log_error(fmt: str, *args) -> None:
    if _verbosity() <= 2:
        _emit("ERROR", fmt % args if args else fmt)


# Fork-equivalent flags (SURVEY.md §5 "Tracing").
trace_ring = TraceFlag("ring")            # ref flag: "rdma" (endpoint.cc:31)
trace_ring_event = TraceFlag("ring_event")  # ref: "rdma_sr_event"
trace_endpoint = TraceFlag("endpoint")
trace_http2 = TraceFlag("http2")          # ref: "http" chttp2 trace
trace_rpc = TraceFlag("rpc")              # ref: "api"/"call_error" surface traces
trace_tpu = TraceFlag("tpu")              # new: device-ring path
