"""tpurpc-verify: concurrency lint, runtime lock checking, ring model checking.

Three layers of correctness tooling for the invariants the data plane lives by
(ARCHITECTURE.md §11 documents the invariants themselves):

* :mod:`tpurpc.analysis.lint` — tpurpc-specific AST passes: lease pairing
  (every ``send_reserve`` reaches commit or abort on all paths), hot-path
  no-copy rules (``b"".join`` / ``from_buffer_copy`` / slice-into-``bytes``
  banned in the ring/pair/h2/codec modules), a lock map (class-declared
  ``_GUARDED_BY`` attributes only mutate under their lock), and monotonic
  clock enforcement (``time.time()`` needs a wall-clock annotation).
* :mod:`tpurpc.analysis.locks` — an opt-in (``TPURPC_DEBUG_LOCKS=1``)
  :class:`CheckedLock` shim that records the cross-thread lock acquisition
  graph, reports cycles as potential deadlocks, and flags locks held across
  blocking calls. Zero overhead when disabled: the factories hand back plain
  ``threading`` primitives.
* :mod:`tpurpc.analysis.ringcheck` — an exhaustive interleaving checker for
  the SPSC ring protocol (single and batched ``write_many`` publishes, wrap,
  credits), with seeded protocol mutants the checker must reject.

CLI: ``python -m tpurpc.analysis`` runs lint + the bounded model check and
exits non-zero on any violation (wired into ``tools/check.sh``).
"""

from tpurpc.analysis.lint import LintViolation, lint_paths, lint_tree  # noqa: F401
from tpurpc.analysis.locks import (  # noqa: F401
    CheckedLock,
    checked_condition,
    lock_violations,
    make_condition,
    make_lock,
    note_blocking,
    reset_lock_state,
)
from tpurpc.analysis.ringcheck import (  # noqa: F401
    CheckResult,
    MUTANTS,
    check_ring,
    default_suite,
    mutant_kill_suite,
)
