"""tpurpc-verify: concurrency lint, runtime lock checking, ring model
checking, deterministic schedule exploration, protocol conformance.

Five layers of correctness tooling for the invariants the data plane lives by
(ARCHITECTURE.md §11/§21 document the invariants themselves):

* :mod:`tpurpc.analysis.lint` — tpurpc-specific AST passes: lease pairing
  (every ``send_reserve`` reaches commit or abort on all paths), hot-path
  no-copy rules (``b"".join`` / ``from_buffer_copy`` / slice-into-``bytes``
  banned in the ring/pair/h2/codec modules), a lock map (class-declared
  ``_GUARDED_BY`` attributes only mutate under their lock), and monotonic
  clock enforcement (``time.time()`` needs a wall-clock annotation).
* :mod:`tpurpc.analysis.locks` — an opt-in (``TPURPC_DEBUG_LOCKS=1``)
  :class:`CheckedLock` shim that records the cross-thread lock acquisition
  graph, reports cycles as potential deadlocks, and flags locks held across
  blocking calls. Zero overhead when disabled: the factories hand back plain
  ``threading`` primitives.
* :mod:`tpurpc.analysis.ringcheck` — an exhaustive interleaving checker for
  the SPSC ring protocol (single and batched ``write_many`` publishes, wrap,
  credits), with seeded protocol mutants the checker must reject.
* :mod:`tpurpc.analysis.schedule` — tpurpc-proof (ISSUE 12): a CHESS-style
  deterministic concurrency explorer that runs the LIVE classes (HandoffRing,
  DecodeScheduler, RdvLink, KvBlockManager) under a cooperative scheduler
  with iterative preemption bounding, hooked through the same
  ``make_lock``/``make_condition`` factory seam TPURPC_DEBUG_LOCKS uses;
  seeded real-code mutants (:mod:`tpurpc.analysis.schedmutants`) must be
  found by exploration.
* :mod:`tpurpc.analysis.protocol` — declared per-entity protocol state
  machines over flight events, with one conformance checker running offline
  on dumps (``python -m tpurpc.analysis protocol --flight <dump>``), in
  tests (``check_events``/``assert_ordered``), and live
  (``TPURPC_VERIFY_PROTOCOL=1`` — violations trip the stall watchdog).

CLI: ``python -m tpurpc.analysis`` runs lint (+ suppression audit) + the
bounded model checks + both new passes and exits non-zero on any violation
(wired into ``tools/check.sh``).
"""

from tpurpc.analysis.lint import (  # noqa: F401
    LintViolation,
    audit_suppressions,
    audit_suppressions_tree,
    lint_paths,
    lint_tree,
)
from tpurpc.analysis.locks import (  # noqa: F401
    CheckedLock,
    checked_condition,
    lock_violations,
    make_condition,
    make_lock,
    note_blocking,
    reset_lock_state,
)
from tpurpc.analysis.ringcheck import (  # noqa: F401
    CheckResult,
    MUTANTS,
    check_ring,
    default_suite,
    mutant_kill_suite,
)
