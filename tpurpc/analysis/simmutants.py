"""tpurpc-simnet: seeded REAL-CODE distributed mutants for the simulator.

The cross-process sibling of :mod:`tpurpc.analysis.schedmutants`: each
mutant is a faithful copy of a live cross-process method with exactly one
DISTRIBUTED discipline removed — a COMPLETE issued before the one-sided
write it announces, a TTL reap that frees instead of quarantining, a
drain that drops the resumable sequences it already accepted, a skipped
ring kick, the pre-fix close/complete park race.
:mod:`tpurpc.analysis.simnet` must kill every one *by message-level
exploration* at small bounds (a violating delivery order or a reported
deadlock, not a sequential unit test) — the proof the simulated fabric
has teeth.

This module's file is added to the instrumented set whenever a simnet
scenario runs, so mutated lines get the same line-granular scheduling
points as the originals. The copies are deliberately line-for-line with
their sources (named in each docstring) so the ONLY behavioral
difference is the seeded bug.
"""

from __future__ import annotations

from typing import Dict

from tpurpc.analysis.schedmutants import Mutant

__all__ = ["SIM_MUTANTS"]


# ---------------------------------------------------------------------------
# ship_complete_before_write — _KvShipper.ship with the COMPLETE hoisted
# above the one-sided writes: nothing orders the receiver's park after
# the landing, so it can park (and later resume) unwritten memory.
# ---------------------------------------------------------------------------

def _ship_complete_before_write(self, grant, handoff, payload, n_tokens,
                                last_token, emitted, timeout):
    """Mutated copy of tpurpc.serving.disagg._KvShipper.ship."""
    import numpy as np

    from tpurpc.serving.disagg import TEST_HOOKS

    chunks = [payload[o:o + grant.block_bytes]
              for o in range(0, len(payload), grant.block_bytes)]
    # MUTANT: COMPLETE first — the write-before-complete ordering the
    # same-QP FIFO (and the simnet link contract) guarantees is gone
    self._complete({"handoff": np.int64(handoff),
                    "n_tokens": np.int32(n_tokens),
                    "last_token": np.int32(last_token),
                    "emitted": np.int32(emitted)}, timeout=timeout)
    wedge = TEST_HOOKS.get("wedge_before_complete")
    if wedge is not None:
        wedge.wait(10)
    self.writer.write_blocks(grant, chunks)


# ---------------------------------------------------------------------------
# reap_free_instead_of_quarantine — DisaggDecode.reap returning a dead
# sender's pending blocks to the FREE list: the straggling one-sided
# write the quarantine exists for can corrupt the next lease.
# ---------------------------------------------------------------------------

def _reap_free_instead_of_quarantine(self, now=None):
    """Mutated copy of tpurpc.serving.disagg.DisaggDecode.reap."""
    import time

    from tpurpc.serving.disagg import _REAPED

    now = time.monotonic() if now is None else now
    with self._lock:
        dead_p = [h for h, p in self._pending.items()
                  if p.deadline <= now]
        pend = [self._pending.pop(h) for h in dead_p]
        dead_k = [k for k, p in self._parked.items()
                  if p.deadline <= now]
        parked = [self._parked.pop(k) for k in dead_k]
    nq = 0
    for p in pend:
        # MUTANT: freed, not quarantined — the dead sender's write is
        # still in flight and these blocks go straight back to the pool
        self.mgr.free_blocks(p.kv)
        self.quarantined_handoffs += 1
        _REAPED.inc()
    for p in parked:
        self.mgr.free_blocks(p.kv, cache_prefix=True)
        _REAPED.inc()
    return nq, len(parked)


# ---------------------------------------------------------------------------
# drain_drops_resumable — DecodeScheduler._admit refusing EVERY waiting
# sequence under drain, including the resumable ones it already accepted
# (a migrated-in sequence killed by the very drain that migrated it).
# ---------------------------------------------------------------------------

def _admit_drain_drops_resumable(self, draining):
    """Mutated copy of tpurpc.serving.scheduler.DecodeScheduler._admit."""
    from tpurpc.serving.scheduler import (SLO_BATCH, SLO_INTERACTIVE,
                                          DrainingError, _PREEMPTS,
                                          _flight, _odyssey)

    admit = []
    drop = []
    preempt = []
    live = []
    for s in self._waiting:
        if s.cancelled:
            drop.append((s, None))
        else:
            live.append(s)
    if not live and not self._swapped:
        return admit, live, drop, preempt
    want_i = sum(1 for s in live if s.slo == SLO_INTERACTIVE)
    if want_i and len(self._running) >= self.max_batch:
        for s in reversed(list(self._running)):
            if want_i <= 0:
                break
            if s.slo == SLO_BATCH:
                self._running.remove(s)
                s.preempted = True
                _flight.emit(_flight.GEN_PREEMPT, self._tag, s.sid,
                             s.slo_code)
                _odyssey.seq_preempt(s.led)
                _PREEMPTS.inc()
                self.preempted_total += 1
                if self._paged:
                    preempt.append(s)
                else:
                    live.insert(0, s)
                want_i -= 1
    slots = self.max_batch - len(self._running)
    budget = self.prefill_budget
    prefills = 0
    keep = []
    for klass in (SLO_INTERACTIVE, SLO_BATCH):
        for s in live:
            if s.slo != klass:
                continue
            if slots <= 0:
                keep.append(s)
                continue
            if draining:
                # MUTANT: the resumable() exemption is gone — a draining
                # scheduler refuses sequences it ALREADY accepted
                drop.append((s, DrainingError(
                    "scheduler draining: prefill refused")))
                continue
            if s.resumable():
                admit.append(s)
                slots -= 1
                continue
            cost = s.prompt_len
            if cost <= budget or prefills == 0:
                admit.append(s)
                slots -= 1
                budget -= cost
                prefills += 1
            else:
                keep.append(s)
    while slots > 0 and self._swapped and not preempt:
        admit.append(self._swapped.pop(0))
        slots -= 1
    keep.sort(key=lambda s: s.sid)
    return admit, keep, drop, preempt


# ---------------------------------------------------------------------------
# ctrl_kick_skipped — CtrlPlane.post without the parked-consumer kick:
# the record is in the ring but the framed wakeup never sails — a
# consumer blocked on the kick sleeps forever (lost wakeup, reported by
# the explorer as a deadlock with the pick trace).
# ---------------------------------------------------------------------------

def _ctrl_kick_skipped(self, op, stream_id, payload, frame_seq, kick):
    """Mutated copy of tpurpc.core.ctrlring.CtrlPlane.post."""
    import time

    from tpurpc.core import transport as _transport
    from tpurpc.core.ctrlring import _KICKS, _LENS_CTRL_BYTES, _LENS_CTRL_NS

    tx = self.tx
    if tx is None or not self.armed:
        return False
    t0 = time.monotonic_ns()
    r = _transport.dispatch("post", self, tx.post, op, stream_id,
                            payload, frame_seq)
    if not r:
        return False
    n = len(payload)
    dt = time.monotonic_ns() - t0
    _LENS_CTRL_BYTES.inc(n)
    _LENS_CTRL_NS.inc(dt)
    if r == 2:
        _KICKS.inc()
        # MUTANT: the kick dispatch is gone — the parked consumer is
        # never woken for the record that raced its park
    return True


# ---------------------------------------------------------------------------
# close_leaks_inflight_complete — the PRE-FIX DisaggDecode.on_complete:
# no _closed re-check at the park insert, so a close() racing the
# unlocked set_length window sweeps the registries and THEN the handler
# parks into them — blocks stranded forever in a closed server.
# ---------------------------------------------------------------------------

def _close_leaks_inflight_complete(self, req, ctx):
    """Mutated copy of tpurpc.serving.disagg.DisaggDecode.on_complete."""
    import time

    import numpy as np

    from tpurpc.rpc.status import StatusCode
    from tpurpc.obs import flight as _flight
    from tpurpc.obs import tracing as _tracing
    from tpurpc.serving.disagg import (ENTRY_BYTES, _HANDOFF_BYTES,
                                       _HANDOFFS, _Parked, _scalar)

    handoff = _scalar(req["handoff"])
    n_tokens = _scalar(req["n_tokens"])
    last_token = _scalar(req["last_token"])
    emitted = _scalar(req["emitted"])
    with self._lock:
        pend = self._pending.pop(handoff, None)
    if pend is None:
        ctx.abort(StatusCode.FAILED_PRECONDITION,
                  f"unknown/expired handoff {handoff} (blocks "
                  "quarantined; offer again)")
    try:
        pend.kv.set_length(n_tokens)
    except Exception as exc:
        self.mgr.quarantine(pend.kv)
        ctx.abort(StatusCode.INVALID_ARGUMENT, str(exc))
    nbytes = n_tokens * ENTRY_BYTES
    with self._lock:
        # MUTANT: no _closed re-check — a close() that ran during the
        # unlocked set_length above already swept this registry
        self._parked[pend.seq_key] = _Parked(
            pend.kv, pend.prompt, last_token, emitted,
            time.monotonic() + self.parked_ttl_s,
            trace=pend.trace, account=pend.account, nbytes=nbytes)
    self.handoffs_in += 1
    _HANDOFFS.inc()
    _HANDOFF_BYTES.inc(nbytes)
    _flight.emit(_flight.KV_SHIP_COMPLETE, self._tag, handoff, nbytes)
    if pend.trace is not None:
        now = time.monotonic_ns()
        _tracing.record("seq-ship", pend.trace, pend.t0_ns,
                        now - pend.t0_ns, handoff=handoff,
                        nbytes=nbytes, account=pend.account)
    return {"ok": np.int32(1)}


def _targets():
    from tpurpc.core.ctrlring import CtrlPlane
    from tpurpc.serving.disagg import DisaggDecode, _KvShipper
    from tpurpc.serving.scheduler import DecodeScheduler

    return _KvShipper, DisaggDecode, DecodeScheduler, CtrlPlane


def _build() -> Dict[str, Mutant]:
    _KvShipper, DisaggDecode, DecodeScheduler, CtrlPlane = _targets()
    muts = [
        Mutant("ship_complete_before_write", "simnet-kvship",
               _KvShipper, "ship", _ship_complete_before_write,
               "COMPLETE issued before the one-sided writes: the receiver "
               "parks (and can resume) memory the bytes never reached"),
        Mutant("reap_free_instead_of_quarantine", "simnet-kvship-death",
               DisaggDecode, "reap", _reap_free_instead_of_quarantine,
               "a dead sender's pending blocks go back to the free list: "
               "its in-flight write corrupts whoever leases them next"),
        Mutant("drain_drops_resumable", "simnet-adopt-drain",
               DecodeScheduler, "_admit", _admit_drain_drops_resumable,
               "drain refuses resumable sequences it already accepted: a "
               "migrated-in sequence dies instead of finishing"),
        Mutant("ctrl_kick_skipped", "simnet-ctrl-kick",
               CtrlPlane, "post", _ctrl_kick_skipped,
               "the parked consumer's framed kick is skipped: a record "
               "that raced the park strands the consumer forever"),
        Mutant("close_leaks_inflight_complete", "simnet-close-complete",
               DisaggDecode, "on_complete", _close_leaks_inflight_complete,
               "no _closed re-check at the park insert: close() sweeps, "
               "the in-flight COMPLETE parks after it, blocks leak"),
    ]
    return {m.name: m for m in muts}


#: name -> Mutant (targets resolved at import of this module)
SIM_MUTANTS: Dict[str, Mutant] = _build()
