"""tpurpc-proof: declared protocol state machines over flight events.

Every chaos test and smoke so far asserted flight-event orderings with a
hand-rolled expected-sequence list — correct once, unreadable forever,
and useless outside its own test. This module makes the orderings
first-class: each transport protocol's per-entity lifecycle is a DECLARED
state machine over the flight vocabulary (:mod:`tpurpc.obs.flight`), and
one conformance checker runs every machine over any event stream —

* **offline**, on a flight dump (``python -m tpurpc.analysis protocol
  --flight <dump.json|dir>`` — also reachable as the top-level
  ``--flight`` convenience), replaying a postmortem against the declared
  protocols;
* **in tests**, via :func:`check_events` (strict) and
  :func:`assert_ordered` — the helper the chaos suites build their
  flight assertions from instead of per-test sequence lists;
* **live**, opt-in via ``TPURPC_VERIFY_PROTOCOL=1``: a tap inside
  ``FlightRecorder.emit`` feeds every event to the machines as it is
  recorded; a violated machine emits a ``proto-violation`` flight event
  and trips the stall watchdog (stage ``protocol``). Cost when off: one
  global None-check per emitted event — and events are EDGES, so a
  healthy loop pays nothing either way (the <3% bench overhead bar is
  measured with the verifier ON).

Machine grammar
---------------

A :class:`Machine` declares ``token(ev)`` (event → symbolic token, or
``None`` to ignore), ``key(ev)`` (the per-entity instance key),
``openers`` (tokens that may create an instance) and ``transitions``
mapping ``(state, token) -> state``; reaching a state in ``terminal``
retires the instance. A token with no transition from the current state
is a violation; a non-opener token for an unknown key is a violation in
STRICT mode (fresh recorders: tests, smokes) and silently skipped in
tolerant mode (wrapped/truncated production dumps, the live verifier —
which by construction starts mid-history). An instance still open at the
end of a dump is NEVER a violation: dumps end mid-flight legitimately.

The declared machines (:data:`MACHINES`) cover the rendezvous lease and
offer lifecycles (events 33–37), KV swap brackets, ship handoffs and
live migration (45–54), decode step brackets (38–39), hedging, drain,
and subchannel ejection (21–28), and the client connection lifecycle
(17–19 with 15/16).

Seeded event-order mutants (:func:`mutant_kill_suite` — e.g.
COMPLETE-before-WRITE, MIG_END-without-MIG_BEGIN) prove the machines
have teeth; they ride the default analysis gate next to ringcheck's.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from tpurpc.obs import flight as _flight

__all__ = [
    "Machine", "ProtocolViolation", "MACHINES",
    "check_events", "check_dump", "check_dumps", "check_cross_process",
    "merge_anchored", "load_dump", "assert_ordered",
    "machine_mutants", "mutant_kill_suite", "self_test",
    "LiveVerifier", "install_live", "uninstall_live", "live_verifier",
]


class ProtocolViolation:
    __slots__ = ("machine", "key", "state", "token", "event", "message",
                 "t_ns")

    def __init__(self, machine: str, key, state: Optional[str], token: str,
                 event: dict, message: str):
        self.machine = machine
        self.key = key
        self.state = state
        self.token = token
        self.event = event
        self.message = message
        self.t_ns = event.get("t_ns", 0)

    def __repr__(self) -> str:
        return (f"{self.machine}[{self.key}]: {self.message} "
                f"(state={self.state!r}, token={self.token!r}, "
                f"event={self.event.get('event')!r} "
                f"a1={self.event.get('a1')} a2={self.event.get('a2')})")

    __str__ = __repr__


class Machine:
    """One declared per-entity protocol (see the module docstring for the
    grammar). ``token``/``key`` are callables over the event dict shape
    :func:`tpurpc.obs.flight.snapshot` produces."""

    def __init__(self, name: str,
                 token: Callable[[dict], Optional[str]],
                 key: Callable[[dict], Optional[tuple]],
                 openers: Dict[str, str],
                 transitions: Dict[Tuple[str, str], str],
                 terminal: Sequence[str] = ("done",),
                 describe: str = ""):
        self.name = name
        self.token = token
        self.key = key
        self.openers = dict(openers)      # token -> state it opens into
        self.transitions = dict(transitions)
        self.terminal = frozenset(terminal)
        self.describe = describe

    def tokens(self) -> frozenset:
        toks = set(self.openers)
        for (_s, t) in self.transitions:
            toks.add(t)
        return frozenset(toks)


class _Checker:
    """Runs every machine over one event stream (instances keyed per
    machine per entity). Settled instances stay tracked in their terminal
    state — a post-settle event is a KNOWN entity misbehaving (the
    complete-before-write signature) even in tolerant mode; an opener on
    a settled instance reopens it (lease-id reuse, re-dials)."""

    #: instance cap (live verifier runs for the process lifetime): when
    #: exceeded, the oldest tracked instances are forgotten — tolerance
    #: degrades gracefully, never memory
    MAX_INSTANCES = 8192

    def __init__(self, machines: Sequence[Machine], strict: bool):
        self.machines = list(machines)
        self.strict = strict
        self.state: Dict[Tuple[str, tuple], str] = {}
        self.violations: List[ProtocolViolation] = []

    def feed(self, ev: dict) -> List[ProtocolViolation]:
        fresh: List[ProtocolViolation] = []
        for m in self.machines:
            token = m.token(ev)
            if token is None:
                continue
            key = m.key(ev)
            if key is None:
                continue
            sk = (m.name, key)
            cur = self.state.get(sk)
            if cur is None:
                opened = m.openers.get(token)
                if opened is not None:
                    self.state[sk] = opened
                    self._bound()
                    continue
                if self.strict:
                    fresh.append(ProtocolViolation(
                        m.name, key, None, token, ev,
                        f"'{token}' without a preceding opener "
                        f"({'/'.join(sorted(m.openers))})"))
                continue
            nxt = m.transitions.get((cur, token))
            if nxt is None and cur in m.terminal and token in m.openers:
                nxt = m.openers[token]  # reopen a settled instance
            if nxt is None:
                fresh.append(ProtocolViolation(
                    m.name, key, cur, token, ev,
                    f"'{token}' is not a legal transition from "
                    f"'{cur}'"))
                continue
            self.state[sk] = nxt
        self.violations.extend(fresh)
        return fresh

    def _bound(self) -> None:
        while len(self.state) > self.MAX_INSTANCES:
            self.state.pop(next(iter(self.state)))

    def open_instances(self) -> Dict[Tuple[str, tuple], str]:
        terminals = {m.name: m.terminal for m in self.machines}
        return {k: v for k, v in self.state.items()
                if v not in terminals.get(k[0], frozenset())}


# ---------------------------------------------------------------------------
# The declared machines.
# ---------------------------------------------------------------------------

def _mk_rdv_lease() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.RDV_CLAIM:
            return "claim"
        if c == F.RDV_WRITE:
            return "write"
        if c == F.RDV_COMPLETE:
            return "complete"
        if c == F.RDV_RELEASE:
            return "release" if ev.get("a1") else None
        return None

    def key(ev):
        c = ev.get("code")
        lease = ev.get("a2") if c == F.RDV_CLAIM else ev.get("a1")
        if not lease:
            return None
        return (ev.get("tag"), lease)

    return Machine(
        "rdv-lease", token, key,
        openers={"claim": "claimed"},
        transitions={
            # sender side: claim -> write -> complete; receiver side never
            # emits write, so claimed -> complete is legal too. A WRITE
            # after the lease settled (the complete-before-write mutant's
            # signature) and any double-settle are violations.
            ("claimed", "write"): "written",
            ("claimed", "complete"): "done",
            ("claimed", "release"): "done",
            ("written", "complete"): "done",
            ("written", "release"): "done",
        },
        describe="one-sided landing-region lease: claim, at most one "
                 "solicited write, exactly one settle (complete/release)")


def _mk_rdv_offer() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.RDV_OFFER:
            return "offer"
        if c == F.RDV_CLAIM:
            return "claim" if ev.get("a1") else None
        if c == F.RDV_RELEASE:
            # a1=0/a2=req is the abandoned-offer release
            return "abandon" if (not ev.get("a1") and ev.get("a2")) else None
        return None

    def key(ev):
        c = ev.get("code")
        req = ev.get("a2") if c == F.RDV_RELEASE else ev.get("a1")
        if not req:
            return None
        return (ev.get("tag"), req)

    return Machine(
        "rdv-offer", token, key,
        openers={"offer": "offered"},
        transitions={
            ("offered", "claim"): "done",
            ("offered", "abandon"): "done",
        },
        describe="solicited transfer negotiation: every claim/abandon "
                 "answers exactly one offer")


def _mk_kv_swap() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.KV_SWAP_BEGIN:
            return "begin-out" if ev.get("a2") == 0 else "begin-in"
        if c == F.KV_SWAP_END:
            return "end-out" if ev.get("a2") == 0 else "end-in"
        return None

    def key(ev):
        return (ev.get("tag"), ev.get("a1"))

    return Machine(
        "kv-swap", token, key,
        openers={"begin-out": "swapping-out", "begin-in": "swapping-in"},
        transitions={
            ("swapping-out", "end-out"): "done",
            ("swapping-in", "end-in"): "done",
        },
        describe="swap brackets pair per sequence and direction; no "
                 "nesting, no END without BEGIN")


def _mk_migration() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.MIG_BEGIN:
            return "begin"
        if c == F.MIG_END:
            return "end"
        return None

    def key(ev):
        return (ev.get("tag"), ev.get("a1"))

    return Machine(
        "migration", token, key,
        openers={"begin": "migrating"},
        transitions={("migrating", "end"): "done"},
        describe="live migration brackets pair per sequence: MIG_END "
                 "always answers a MIG_BEGIN, never nests")


def _mk_kv_ship() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.KV_SHIP_OFFER:
            return "offer"
        if c == F.KV_SHIP_COMPLETE:
            return "complete"
        return None

    def key(ev):
        h = ev.get("a1")
        if not h:
            return None
        return (ev.get("tag"), h)

    return Machine(
        "kv-ship", token, key,
        openers={"offer": "offered"},
        transitions={("offered", "complete"): "done"},
        describe="block-granular KV handoff: COMPLETE answers exactly "
                 "one OFFER per handoff id")


def _mk_gen_step() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.GEN_STEP_BEGIN:
            return "begin"
        if c == F.GEN_STEP_END:
            return "end"
        return None

    def key(ev):
        return (ev.get("tag"),)

    return Machine(
        "gen-step", token, key,
        openers={"begin": "stepping"},
        transitions={("stepping", "end"): "done"},
        describe="device-step brackets strictly alternate per scheduler "
                 "(the loop is single-threaded by construction)")


def _mk_hedge() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.HEDGE_FIRED:
            return "fired"
        if c == F.HEDGE_WON:
            return "won"
        if c == F.HEDGE_CANCELLED:
            return "cancelled"
        return None

    def key(ev):
        return (ev.get("tag"),)

    # per-call-tag view: hedges fire, one attempt wins, losers cancel.
    # Counting is out of a finite machine's reach; the ordering claims —
    # nothing settles before something fired — are exactly what the
    # chaos tests asserted by hand.
    return Machine(
        "hedge", token, key,
        openers={"fired": "hedging"},
        transitions={
            ("hedging", "fired"): "hedging",
            ("hedging", "cancelled"): "hedging",
            ("hedging", "won"): "settled",
            ("settled", "cancelled"): "settled",
            ("settled", "won"): "settled",
            ("settled", "fired"): "hedging",
        },
        describe="no hedge settles (won/cancelled) before one fired on "
                 "the call")


def _mk_drain() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.DRAIN_BEGIN:
            return "begin"
        if c == F.DRAIN_END:
            return "end"
        return None

    def key(ev):
        return (ev.get("tag"),)

    return Machine(
        "drain", token, key,
        openers={"begin": "draining"},
        transitions={("draining", "end"): "done"},
        describe="drain brackets pair per server; no END without BEGIN")


def _mk_subch() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.SUBCH_EJECT:
            return "eject"
        if c == F.SUBCH_REINSTATE:
            return "reinstate"
        return None

    def key(ev):
        return (ev.get("tag"), ev.get("a1"))

    return Machine(
        "subchannel", token, key,
        openers={"eject": "ejected"},
        transitions={("ejected", "reinstate"): "done"},
        describe="outlier ejection pairs: reinstate answers eject, no "
                 "double-eject")


def _mk_conn() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.CONN_CONNECT:
            return "connect"
        if c == F.CALL_FIRST_OK:
            return "first-ok"
        if c == F.CONN_DEAD:
            return "dead"
        return None

    def key(ev):
        return (ev.get("tag"),)

    # the tag is "conn:<peer>" — SHARED by every connection instance to
    # one peer, so several lifecycles interleave under one key. The
    # machine is therefore a per-peer hub: once any connection to the
    # peer existed, further first-OK/death events are legal in any
    # interleaving; what it still proves (strictly) is that NOTHING —
    # no first-OK, no death — precedes the peer's first connect.
    return Machine(
        "conn", token, key,
        openers={"connect": "connected"},
        transitions={
            ("connected", "first-ok"): "serving",
            ("connected", "dead"): "done",
            ("serving", "dead"): "done",
            ("serving", "first-ok"): "serving",
            ("done", "dead"): "done",
            ("done", "first-ok"): "done",
            ("connected", "connect"): "connected",
            ("serving", "connect"): "connected",
        },
        describe="per-peer connection lifecycle: no first-OK or death "
                 "before the peer's first connect")


def _mk_ctrl_ring() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.CTRL_ADOPT:
            return "adopt"
        if c == F.CTRL_SPIN:
            return "spin"
        if c == F.CTRL_PARK:
            return "park"
        return None

    def key(ev):
        return (ev.get("tag"),)

    # the tag is "ctrl:<peer>" — shared across reconnects to one peer, so
    # re-adoption from any state is legal; what the machine proves is that
    # no consumer ever spins or parks a ring that was never adopted
    return Machine(
        "ctrl-ring", token, key,
        openers={"adopt": "parked"},
        transitions={
            ("parked", "spin"): "hot",
            ("parked", "park"): "parked",
            ("parked", "adopt"): "parked",
            ("hot", "park"): "parked",
            ("hot", "spin"): "hot",
            ("hot", "adopt"): "parked",
        },
        terminal=(),
        describe="descriptor-ring consumer lifecycle: no spin/park flip "
                 "before the link adopted a ring")


def _mk_ctrl_stall() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.CTRL_STALL_BEGIN:
            return "begin"
        if c == F.CTRL_STALL_END:
            return "end"
        return None

    def key(ev):
        return (ev.get("tag"),)

    return Machine(
        "ctrl-stall", token, key,
        openers={"begin": "stalled"},
        transitions={("stalled", "end"): "done"},
        describe="ring-full stall brackets pair per link: no END without "
                 "BEGIN, no nesting")


def _mk_seq_journey() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.SEQ_SUBMIT:
            return "submit"
        if c == F.GEN_JOIN:
            return "join"
        if c == F.SEQ_FIRST_TOKEN:
            return "first-token"
        if c == F.GEN_PREEMPT:
            return "preempt"
        if c == F.GEN_LEAVE:
            return "leave"
        if c == F.GEN_RETIRE:
            return "retire"
        if c == F.SEQ_DETACH:
            return "detach"
        return None

    def key(ev):
        sid = ev.get("a1")
        if not sid:
            return None
        return (ev.get("tag"), sid)

    # tpurpc-odyssey (ISSUE 15): one sequence's lifecycle per (scheduler
    # tag, seq id). submit opens; join admits (a failed prefill retires
    # straight from submitted); the single first-token edge happens once
    # and only in the running window (first-token after retire — the
    # "token after retire" bug — has no transition out of done/detached);
    # preempt parks and a later join resumes; detach hands the sequence
    # to a migration (the journey continues on the peer under a fresh
    # sid, same trace). Shed sequences never open: the shed decision
    # precedes SEQ_SUBMIT by construction.
    return Machine(
        "seq-journey", token, key,
        openers={"submit": "submitted"},
        transitions={
            ("submitted", "join"): "running",
            ("submitted", "retire"): "done",    # prefill failed, row alone
            ("submitted", "leave"): "done",     # dropped at admission
            ("submitted", "detach"): "done",    # adopted-waiting, migrated
            ("running", "first-token"): "streaming",
            ("running", "retire"): "done",
            ("running", "leave"): "done",
            ("running", "preempt"): "parked",
            ("running", "detach"): "done",
            ("streaming", "preempt"): "parked",
            ("streaming", "retire"): "done",
            ("streaming", "leave"): "done",
            ("streaming", "detach"): "done",
            ("parked", "join"): "streaming",
            ("parked", "leave"): "done",
            ("parked", "detach"): "done",
        },
        describe="sequence lifecycle: submit before join, one first-token "
                 "inside the running window, no token/membership event "
                 "after retire/leave/detach")


def _mk_park() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.PAIR_PARK:
            return "park"
        if c == F.PAIR_UNPARK:
            return "unpark"
        return None

    def key(ev):
        return (ev.get("tag"),)

    # tpurpc-hive (ISSUE 16): one pair's park episodes, keyed per pair
    # flight tag. PARK is emitted only when the regions actually went to
    # the RingPool, UNPARK only when fresh rings were leased back — so a
    # double PARK (regions pooled twice) or an UNPARK with nothing parked
    # (a lease the accounting would never see returned) are both real
    # bugs, not telemetry noise. Settled episodes reopen on the next
    # park (a pair parks many times over its life).
    return Machine(
        "pair-park", token, key,
        openers={"park": "parked"},
        transitions={("parked", "unpark"): "done"},
        describe="idle-pair parking episodes per pair: no double-park, "
                 "unpark only after park")


def _mk_slo() -> Machine:
    F = _flight

    def token(ev):
        c = ev.get("code")
        if c == F.SLO_FIRING:
            return "firing"
        if c == F.SLO_RESOLVED:
            return "resolved"
        return None

    def key(ev):
        # one alert episode per (objective tag, budget track)
        return (ev.get("tag"), ev.get("a1"))

    return Machine(
        "slo-alert", token, key,
        openers={"firing": "firing"},
        transitions={("firing", "resolved"): "done"},
        describe="tpurpc-argus burn-rate alert episodes bracket per "
                 "(objective, track): no double-fire without a resolve, "
                 "no orphan resolve")


#: every declared machine, in evaluation order
MACHINES: List[Machine] = [
    _mk_rdv_lease(), _mk_rdv_offer(), _mk_kv_swap(), _mk_migration(),
    _mk_kv_ship(), _mk_gen_step(), _mk_hedge(), _mk_drain(), _mk_subch(),
    _mk_conn(), _mk_ctrl_ring(), _mk_ctrl_stall(), _mk_slo(),
    _mk_seq_journey(), _mk_park(),
]


# ---------------------------------------------------------------------------
# Offline conformance.
# ---------------------------------------------------------------------------

def check_events(events: Iterable[dict], strict: bool = True,
                 machines: Optional[Sequence[Machine]] = None
                 ) -> List[ProtocolViolation]:
    """Run every machine over a time-ordered event stream (the
    :func:`tpurpc.obs.flight.snapshot` dict shape). ``strict=False``
    tolerates streams that begin mid-history (wrapped rings, production
    dumps): non-opener events for unknown entities are skipped instead of
    flagged."""
    chk = _Checker(machines if machines is not None else MACHINES, strict)
    for ev in sorted(events, key=lambda e: e.get("t_ns", 0)):
        chk.feed(ev)
    return chk.violations


def load_dump(path: str) -> List[dict]:
    """Events from one flight dump file: a JSON list of event dicts, or
    any JSON object carrying them under an ``events`` key (the
    ``/debug/flight`` body shape)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("events", [])
    if not isinstance(data, list):
        raise ValueError(f"{path}: not a flight dump (want a list of "
                        "events or {'events': [...]})")
    return data


def check_dump(path: str, strict: bool = False
               ) -> Tuple[int, List[ProtocolViolation]]:
    """Conformance over one dump file, or every ``*.json`` in a directory
    (the ``TPURPC_FLIGHT_DUMP`` output layout). Returns
    ``(events_checked, violations)``. Offline dumps default to TOLERANT:
    a dump may start mid-history."""
    return check_dumps([path], strict=strict)


def _expand_dump_paths(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for fn in sorted(os.listdir(path)):
                if fn.endswith(".json"):
                    out.append(os.path.join(path, fn))
        else:
            out.append(path)
    return out


def _load_doc(path: str) -> Tuple[List[dict], Optional[dict]]:
    """``(events, clock_anchor-or-None)`` from one dump file; the anchor
    is present when the dump was written by the anchored exit hook
    (``TPURPC_FLIGHT_DUMP`` since ISSUE 17) or a ``/debug/flight`` body
    that carries one."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        events = data.get("events", [])
        if not isinstance(events, list):
            raise ValueError(f"{path}: 'events' is not a list")
        return events, data.get("clock_anchor")
    if not isinstance(data, list):
        raise ValueError(f"{path}: not a flight dump (want a list of "
                         "events or {'events': [...]})")
    return data, None


#: per-process tag namespace width in the merged stream — tags are
#: process-local ints; two processes' tag 7 must not collide into one
#: machine instance when their dumps merge
_MERGE_TAG_SHIFT = 48


def merge_anchored(docs: Sequence[Tuple[List[dict], dict]]) -> List[dict]:
    """Several per-process event streams → ONE stream on the shared wall
    clock.  Each dump's ``clock_anchor`` gives the rebase
    ``wall = t_mono - mono_ns + wall_ns``; tags are namespaced per
    process (``(i+1) << 48 | tag``) so per-entity machine keys never
    collide across processes.  Per-process relative order is preserved
    exactly (a constant offset per stream + a stable sort)."""
    merged: List[dict] = []
    for i, (events, anchor) in enumerate(docs):
        off = int(anchor["wall_ns"]) - int(anchor["mono_ns"])
        ns = (i + 1) << _MERGE_TAG_SHIFT
        for ev in events:
            e2 = dict(ev)
            e2["t_ns"] = int(ev.get("t_ns", 0)) + off
            e2["tag"] = ns | (int(ev.get("tag", 0))
                              & ((1 << _MERGE_TAG_SHIFT) - 1))
            merged.append(e2)
    merged.sort(key=lambda e: e.get("t_ns", 0))
    return merged


def check_cross_process(merged: Sequence[dict],
                        slack_ns: int = 0) -> List[ProtocolViolation]:
    """The merged-stream pairing rule no single process can check:
    every SUCCESSFUL migration (``MIG_END`` with ``a2 == 1``) must cover
    at least one ``KV_SHIP_COMPLETE`` — emitted by the DESTINATION
    process — between its ``MIG_BEGIN`` and itself.  The source's own
    dump shows only the bracket; the landing proof for the bytes it
    claims it moved lives in the other process's stream.  ``slack_ns``
    widens the bracket by the summed anchor uncertainties (two rebased
    clocks agree only to within their bracketing error)."""
    F = _flight
    out: List[ProtocolViolation] = []
    begins: Dict[tuple, int] = {}
    completes: List[int] = []
    for ev in merged:
        c = ev.get("code")
        if c == F.KV_SHIP_COMPLETE:
            completes.append(int(ev.get("t_ns", 0)))
        elif c == F.MIG_BEGIN:
            begins[(ev.get("tag"), ev.get("a1"))] = int(ev.get("t_ns", 0))
        elif c == F.MIG_END and ev.get("a2") == 1:
            k = (ev.get("tag"), ev.get("a1"))
            t0 = begins.pop(k, None)
            if t0 is None:
                continue  # bracket opened before the dump: tolerated
            t1 = int(ev.get("t_ns", 0))
            if not any(t0 - slack_ns <= t <= t1 + slack_ns
                       for t in completes):
                out.append(ProtocolViolation(
                    "xproc-mig-ship", k, "migrating", "end", ev,
                    "successful migration with NO KV_SHIP_COMPLETE in "
                    "ANY process between MIG_BEGIN and MIG_END — the "
                    "bytes the source claims it moved never landed "
                    "anywhere"))
    return out


def check_dumps(paths: Iterable[str], strict: bool = False
                ) -> Tuple[int, List[ProtocolViolation]]:
    """Conformance over one or SEVERAL per-process dumps of one run
    (``protocol --flight A.json --flight B.json``, ISSUE 17).

    Each file (directories expand to their ``*.json``) is first checked
    on its own clock exactly as :func:`check_dump` always has.  With two
    or more dumps that all carry a ``clock_anchor``, the streams are
    additionally rebased onto the shared wall clock, merged, and the
    CROSS-PROCESS pairing rules run over the merged stream
    (:func:`check_cross_process`).  The per-entity machines are NOT
    re-run on the merged stream: every machine key includes the
    process-local ``tag``, so a merged machine pass would partition back
    into the per-file passes and report each violation twice.

    Anchor policy: EXPLICITLY passing several paths demands
    mergeability — an un-anchored dump among them is reported as a
    violation, not silently skipped (a quiet skip reads as 'merged
    stream checked' when it wasn't).  A single DIRECTORY argument (the
    ``TPURPC_FLIGHT_DUMP`` layout, which may hold pre-anchor dumps)
    merges opportunistically: all anchored → merged check; otherwise
    per-file only, exactly the historical behavior."""
    explicit = list(paths)
    files = _expand_dump_paths(explicit)
    docs: List[Tuple[str, List[dict], Optional[dict]]] = []
    total = 0
    out: List[ProtocolViolation] = []
    for p in files:
        events, anchor = _load_doc(p)
        docs.append((p, events, anchor))
        total += len(events)
        out.extend(check_events(events, strict=strict))
    if len(docs) >= 2:
        missing = [p for p, _e, a in docs if not a]
        if missing:
            if len(explicit) >= 2:
                out.append(ProtocolViolation(
                    "xproc-merge",
                    tuple(os.path.basename(p) for p in missing),
                    None, "anchor", {"event": "merge", "t_ns": 0},
                    "multi-dump check requested but these dumps carry "
                    "no clock_anchor — cannot rebase onto one wall "
                    "clock (re-record with TPURPC_FLIGHT_DUMP)"))
        else:
            anchors = [a for _p, _e, a in docs]
            slack = sum(int(a.get("uncertainty_ns", 0)) for a in anchors)
            merged = merge_anchored([(e, a) for _p, e, a in docs])
            out.extend(check_cross_process(merged, slack_ns=slack))
    return total, out


# ---------------------------------------------------------------------------
# The test-suite helper (replaces the hand-rolled expected-order lists).
# ---------------------------------------------------------------------------

def assert_ordered(events: Sequence[dict], steps: Sequence,
                   since_ns: int = 0) -> List[dict]:
    """Assert ``steps`` occur in time order within ``events`` and return
    the matched events. Each step is an event NAME (``"conn-dead"``), a
    tuple of alternative names (``("conn-dead", "peer-death")``), or
    either paired with a ``{field: value}`` filter constraining
    ``tag``/``a1``/``a2``/…; matching is first-at-or-after the previous
    step's stamp. The chaos suites build their flight-order assertions
    from this ONE helper plus :func:`check_events` over the same
    snapshot — the declared machines carry the per-entity legality, this
    carries the cross-entity ordering."""
    t = since_ns
    matched: List[dict] = []
    ordered = sorted(events, key=lambda e: e.get("t_ns", 0))
    for step in steps:
        if isinstance(step, str):
            names, where = (step,), {}
        elif (len(step) == 2 and isinstance(step[1], dict)):
            names = ((step[0],) if isinstance(step[0], str)
                     else tuple(step[0]))
            where = step[1]
        else:
            names, where = tuple(step), {}
        hit = None
        for ev in ordered:
            if ev.get("t_ns", 0) < t or ev.get("event") not in names:
                continue
            if all(ev.get(k) == v for k, v in where.items()):
                hit = ev
                break
        if hit is None:
            seen = [e.get("event") for e in ordered
                    if e.get("t_ns", 0) >= since_ns]
            raise AssertionError(
                f"flight order: no {'/'.join(names)} matching {where} "
                f"at/after t={t} (events after since_ns: {seen})")
        matched.append(hit)
        t = hit.get("t_ns", 0)
    return matched


# ---------------------------------------------------------------------------
# Seeded event-order mutants: the machines must have teeth.
# ---------------------------------------------------------------------------

def _ev(code: int, tag: int = 7, a1: int = 0, a2: int = 0,
        t_ns: int = 0) -> dict:
    return {"t_ns": t_ns, "code": code, "event":
            _flight.EVENT_NAMES.get(code, "?"), "tag": tag,
            "entity": "-", "tid": 0, "a1": a1, "a2": a2}


def _good_trace() -> List[dict]:
    """A synthesized clean run exercising every machine — the self-test's
    'the machines accept the declared protocols' half."""
    F = _flight
    t = iter(range(1, 10_000))
    e = []
    # connection up, serving, down
    e += [_ev(F.CONN_CONNECT, tag=1, t_ns=next(t)),
          _ev(F.CALL_FIRST_OK, tag=1, t_ns=next(t))]
    # solicited rendezvous transfer, then an abandoned offer
    e += [_ev(F.RDV_OFFER, tag=2, a1=11, a2=1 << 20, t_ns=next(t)),
          _ev(F.RDV_CLAIM, tag=2, a1=11, a2=501, t_ns=next(t)),
          _ev(F.RDV_WRITE, tag=2, a1=501, a2=1 << 20, t_ns=next(t)),
          _ev(F.RDV_COMPLETE, tag=2, a1=501, a2=1 << 20, t_ns=next(t)),
          _ev(F.RDV_OFFER, tag=2, a1=12, a2=1 << 20, t_ns=next(t)),
          _ev(F.RDV_RELEASE, tag=2, a1=0, a2=12, t_ns=next(t))]
    # receiver-side lease: claim then complete (no write event)
    e += [_ev(F.RDV_OFFER, tag=3, a1=21, a2=1 << 18, t_ns=next(t)),
          _ev(F.RDV_CLAIM, tag=3, a1=21, a2=601, t_ns=next(t)),
          _ev(F.RDV_COMPLETE, tag=3, a1=601, a2=1 << 18, t_ns=next(t))]
    # one full sequence journey (tpurpc-odyssey): submit -> join ->
    # first token -> preempt -> resume-join -> retire; and an adopted
    # sequence detached mid-life (migrated out)
    e += [_ev(F.SEQ_SUBMIT, tag=4, a1=9, a2=32, t_ns=next(t)),
          _ev(F.GEN_JOIN, tag=4, a1=9, a2=32, t_ns=next(t)),
          _ev(F.SEQ_FIRST_TOKEN, tag=4, a1=9, a2=1800, t_ns=next(t)),
          _ev(F.GEN_PREEMPT, tag=4, a1=9, a2=1, t_ns=next(t)),
          _ev(F.GEN_JOIN, tag=4, a1=9, a2=0, t_ns=next(t)),
          _ev(F.GEN_RETIRE, tag=4, a1=9, a2=24, t_ns=next(t)),
          _ev(F.SEQ_SUBMIT, tag=4, a1=10, a2=16, t_ns=next(t)),
          _ev(F.GEN_JOIN, tag=4, a1=10, a2=16, t_ns=next(t)),
          _ev(F.SEQ_FIRST_TOKEN, tag=4, a1=10, a2=900, t_ns=next(t)),
          _ev(F.SEQ_DETACH, tag=4, a1=10, a2=17, t_ns=next(t))]
    # decode steps bracketing a swap-out/in pair and one migration
    e += [_ev(F.GEN_STEP_BEGIN, tag=4, a1=2, t_ns=next(t)),
          _ev(F.GEN_STEP_END, tag=4, a1=2, a2=2, t_ns=next(t)),
          _ev(F.KV_SWAP_BEGIN, tag=5, a1=9, a2=0, t_ns=next(t)),
          _ev(F.KV_SWAP_END, tag=5, a1=9, a2=0, t_ns=next(t)),
          _ev(F.KV_SWAP_BEGIN, tag=5, a1=9, a2=1, t_ns=next(t)),
          _ev(F.KV_SWAP_END, tag=5, a1=9, a2=1, t_ns=next(t)),
          _ev(F.MIG_BEGIN, tag=4, a1=9, a2=40, t_ns=next(t)),
          _ev(F.MIG_END, tag=4, a1=9, a2=1, t_ns=next(t)),
          _ev(F.KV_SHIP_OFFER, tag=5, a1=77, a2=4096, t_ns=next(t)),
          _ev(F.KV_SHIP_COMPLETE, tag=5, a1=77, a2=4096, t_ns=next(t))]
    # descriptor-ring control plane: adopt, hot/parked flips, one ring-full
    # stall bracket (tpurpc-pulse)
    e += [_ev(F.CTRL_ADOPT, tag=8, a1=64, a2=128, t_ns=next(t)),
          _ev(F.CTRL_SPIN, tag=8, a1=0, t_ns=next(t)),
          _ev(F.CTRL_PARK, tag=8, a1=12, t_ns=next(t)),
          _ev(F.CTRL_SPIN, tag=8, a1=12, t_ns=next(t)),
          _ev(F.CTRL_STALL_BEGIN, tag=8, a1=64, t_ns=next(t)),
          _ev(F.CTRL_STALL_END, tag=8, t_ns=next(t))]
    # tpurpc-hive: two park episodes on one pair (park -> unpark, reopen)
    # and an accept-shed edge (unkeyed by any machine, must stay clean)
    e += [_ev(F.PAIR_PARK, tag=9, a1=16384, t_ns=next(t)),
          _ev(F.PAIR_UNPARK, tag=9, a1=16512, a2=1, t_ns=next(t)),
          _ev(F.PAIR_PARK, tag=9, a1=16384, t_ns=next(t)),
          _ev(F.PAIR_UNPARK, tag=9, a1=16512, a2=0, t_ns=next(t)),
          _ev(F.ACCEPT_SHED, tag=9, a1=64, a2=50, t_ns=next(t))]
    # hedging, drain, ejection
    e += [_ev(F.HEDGE_FIRED, tag=6, a1=1, t_ns=next(t)),
          _ev(F.HEDGE_WON, tag=6, a1=0, t_ns=next(t)),
          _ev(F.HEDGE_CANCELLED, tag=6, a1=1, t_ns=next(t)),
          _ev(F.DRAIN_BEGIN, tag=1, a1=3, t_ns=next(t)),
          _ev(F.DRAIN_END, tag=1, a1=0, t_ns=next(t)),
          _ev(F.SUBCH_EJECT, tag=6, a1=2, a2=0, t_ns=next(t)),
          _ev(F.SUBCH_REINSTATE, tag=6, a1=2, t_ns=next(t)),
          _ev(F.CONN_DEAD, tag=1, a1=1, t_ns=next(t))]
    return e


def machine_mutants() -> Dict[str, List[dict]]:
    """Seeded BAD traces, each violating one declared protocol — the
    machines must flag every one (and accept :func:`_good_trace`)."""
    F = _flight
    return {
        # the acceptance-named pair first
        "complete_before_write": [
            _ev(F.RDV_OFFER, tag=2, a1=11, a2=1 << 20, t_ns=1),
            _ev(F.RDV_CLAIM, tag=2, a1=11, a2=501, t_ns=2),
            _ev(F.RDV_COMPLETE, tag=2, a1=501, a2=1 << 20, t_ns=3),
            _ev(F.RDV_WRITE, tag=2, a1=501, a2=1 << 20, t_ns=4),
        ],
        "mig_end_without_begin": [
            _ev(F.GEN_STEP_BEGIN, tag=4, a1=1, t_ns=1),
            _ev(F.GEN_STEP_END, tag=4, a1=1, t_ns=2),
            _ev(F.MIG_END, tag=4, a1=9, a2=1, t_ns=3),
        ],
        "double_claim": [
            _ev(F.RDV_OFFER, tag=2, a1=11, a2=1 << 20, t_ns=1),
            _ev(F.RDV_CLAIM, tag=2, a1=11, a2=501, t_ns=2),
            _ev(F.RDV_CLAIM, tag=2, a1=11, a2=502, t_ns=3),
        ],
        "swap_end_wrong_direction": [
            _ev(F.KV_SWAP_BEGIN, tag=5, a1=9, a2=0, t_ns=1),
            _ev(F.KV_SWAP_END, tag=5, a1=9, a2=1, t_ns=2),
        ],
        "nested_step_begin": [
            _ev(F.GEN_STEP_BEGIN, tag=4, a1=1, t_ns=1),
            _ev(F.GEN_STEP_BEGIN, tag=4, a1=2, t_ns=2),
        ],
        "drain_end_without_begin": [
            _ev(F.CONN_CONNECT, tag=1, t_ns=1),
            _ev(F.DRAIN_END, tag=1, a1=0, t_ns=2),
        ],
        "hedge_won_before_fired": [
            _ev(F.HEDGE_WON, tag=6, a1=1, t_ns=1),
            _ev(F.HEDGE_FIRED, tag=6, a1=1, t_ns=2),
        ],
        "reinstate_without_eject": [
            _ev(F.SUBCH_EJECT, tag=6, a1=1, t_ns=1),
            _ev(F.SUBCH_REINSTATE, tag=6, a1=2, t_ns=2),
        ],
        "ship_complete_unoffered": [
            _ev(F.KV_SHIP_OFFER, tag=5, a1=77, a2=4096, t_ns=1),
            _ev(F.KV_SHIP_COMPLETE, tag=5, a1=78, a2=4096, t_ns=2),
        ],
        "first_ok_without_connect": [
            _ev(F.CALL_FIRST_OK, tag=1, t_ns=1),
        ],
        # tpurpc-odyssey: the seq-journey machine's teeth — a token after
        # the sequence retired, and membership without a submit
        "seq_token_after_retire": [
            _ev(F.SEQ_SUBMIT, tag=4, a1=9, a2=8, t_ns=1),
            _ev(F.GEN_JOIN, tag=4, a1=9, a2=8, t_ns=2),
            _ev(F.SEQ_FIRST_TOKEN, tag=4, a1=9, a2=500, t_ns=3),
            _ev(F.GEN_RETIRE, tag=4, a1=9, a2=4, t_ns=4),
            _ev(F.SEQ_FIRST_TOKEN, tag=4, a1=9, a2=900, t_ns=5),
        ],
        "seq_join_without_submit": [
            _ev(F.GEN_JOIN, tag=4, a1=9, a2=8, t_ns=1),
            _ev(F.GEN_RETIRE, tag=4, a1=9, a2=4, t_ns=2),
        ],
        # tpurpc-pulse: the descriptor-ring machines' teeth
        "ctrl_spin_before_adopt": [
            _ev(F.CTRL_SPIN, tag=8, a1=0, t_ns=1),
            _ev(F.CTRL_ADOPT, tag=8, a1=64, a2=128, t_ns=2),
        ],
        "ctrl_stall_end_without_begin": [
            _ev(F.CTRL_ADOPT, tag=8, a1=64, a2=128, t_ns=1),
            _ev(F.CTRL_STALL_END, tag=8, t_ns=2),
        ],
        # tpurpc-hive: the pair-park machine's teeth — regions pooled
        # twice without an intervening unpark
        "double_park": [
            _ev(F.PAIR_PARK, tag=9, a1=16384, t_ns=1),
            _ev(F.PAIR_PARK, tag=9, a1=16384, t_ns=2),
        ],
        "unpark_without_park": [
            _ev(F.PAIR_UNPARK, tag=9, a1=16512, a2=0, t_ns=1),
        ],
    }


def mutant_kill_suite(verbose: bool = False) -> Dict[str, bool]:
    kills: Dict[str, bool] = {}
    for name, trace in sorted(machine_mutants().items()):
        v = check_events(trace, strict=True)
        kills[name] = bool(v)
        if verbose:
            print(f"protocol mutant {name}: "
                  f"{'KILLED' if v else 'SURVIVED'}"
                  + (f" ({v[0]})" if v else ""))
    return kills


def self_test(verbose: bool = False) -> List[str]:
    """The default-gate protocol pass: the good trace must check clean
    (strict) and every seeded event-order mutant must be flagged.
    Returns failure strings (empty = pass)."""
    failures: List[str] = []
    good = check_events(_good_trace(), strict=True)
    if good:
        failures.extend(f"good trace rejected: {v}" for v in good)
    for name, killed in mutant_kill_suite(verbose=verbose).items():
        if not killed:
            failures.append(f"event-order mutant SURVIVED: {name}")
    if verbose and not failures:
        print(f"protocol: {len(MACHINES)} machines, good trace clean, "
              f"{len(machine_mutants())} seeded mutants killed")
    return failures


# ---------------------------------------------------------------------------
# The live verifier (TPURPC_VERIFY_PROTOCOL=1).
# ---------------------------------------------------------------------------

class LiveVerifier:
    """Feeds every recorded flight event through the machines as it is
    emitted (tolerant mode: the process's history predates us). On a
    violation: one ``proto-violation`` flight event (a1 = machine index,
    a2 = offending code) and one stall-watchdog external trip naming the
    machine. Violations are also kept (bounded) for tests and
    ``/debug``-style introspection."""

    MAX_KEPT = 256

    def __init__(self, machines: Optional[Sequence[Machine]] = None):
        self._chk = _Checker(machines if machines is not None else MACHINES,
                             strict=False)
        self._mu = threading.Lock()
        self.violations: List[ProtocolViolation] = []
        self.checked = 0

    def __call__(self, code: int, tag: int, a1: int, a2: int) -> None:
        if code == _flight.PROTO_VIOLATION:
            return  # our own breadcrumb
        ev = {"t_ns": 0, "code": code,
              "event": _flight.EVENT_NAMES.get(code, "?"),
              "tag": tag, "a1": a1, "a2": a2}
        with self._mu:
            self.checked += 1
            fresh = self._chk.feed(ev)
            if fresh and len(self.violations) < self.MAX_KEPT:
                self.violations.extend(fresh)
        for v in fresh:
            self._report(v, code, tag)

    def _report(self, v: ProtocolViolation, code: int, tag: int) -> None:
        try:
            idx = next((i for i, m in enumerate(self._chk.machines)
                        if m.name == v.machine), 0)
            _flight.emit(_flight.PROTO_VIOLATION, tag, idx, code)
            from tpurpc.obs import watchdog as _watchdog

            _watchdog.get().external_trip(
                "protocol", f"machine:{v.machine}", str(v))
        except Exception:
            pass  # verification must never take the transport down


def install_live(machines: Optional[Sequence[Machine]] = None
                 ) -> LiveVerifier:
    """Arm the live verifier on the process-wide flight recorder (the
    ``TPURPC_VERIFY_PROTOCOL=1`` switch calls this from flight.py)."""
    v = LiveVerifier(machines)
    _flight.set_verify_hook(v)
    return v


def uninstall_live() -> None:
    _flight.set_verify_hook(None)


def live_verifier() -> Optional[LiveVerifier]:
    hook = _flight.verify_hook()
    return hook if isinstance(hook, LiveVerifier) else None


# TPURPC_VERIFY_PROTOCOL=1 arming happens on whichever side finishes
# importing LAST: flight.py's bottom installs when flight is imported
# first (the common order); when THIS module is imported first, flight's
# attempt sees a partially initialized protocol and declines — so we
# install here once the module is whole.
if (os.environ.get("TPURPC_VERIFY_PROTOCOL", "") == "1"
        and _flight.verify_hook() is None):
    install_live()
